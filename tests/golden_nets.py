"""Canonical topology builders for the golden-config regression suite —
the analog of the reference's trainer_config_helpers/tests/configs/*.py
fixtures (~40 checked-in configs with .protostr goldens).

Each builder returns (Topology, feed_fn) where feed_fn(rng) produces a feed
dict exercising the net.  Used by tests/test_golden_configs.py (protostr
golden + rebuild equivalence) and tests/golden/regen.py.
"""

import numpy as np

import paddle_tpu.nn as nn
import paddle_tpu.v2.networks as networks


def _cls_feed(names_shapes, n_cls=3, B=2):
    def feed(rng):
        out = {}
        for name, spec in names_shapes.items():
            kind = spec[0]
            if kind == "dense":
                out[name] = rng.rand(B, *spec[1]).astype(np.float32)
            elif kind == "ids_seq":
                T, V = spec[1]
                out[name] = (rng.randint(0, V, (B, T)).astype(np.int32),
                             np.array([T, max(T - 2, 1)], np.int32)[:B])
            elif kind == "seq":
                T, D = spec[1]
                out[name] = (rng.randn(B, T, D).astype(np.float32),
                             np.array([T, max(T - 2, 1)], np.int32)[:B])
            elif kind == "int":
                out[name] = rng.randint(0, spec[1], (B, 1)).astype(np.int32)
            elif kind == "label":
                out[name] = rng.randint(0, n_cls, (B, 1)).astype(np.int32)
            elif kind == "labels_seq":
                T, C = spec[1]
                out[name] = (rng.randint(0, C, (B, T)).astype(np.int32),
                             np.array([T, max(T - 2, 1)], np.int32)[:B])
        return out

    return feed


def fc_dropout_net():
    x = nn.data("x", size=12)
    h1 = nn.fc(x, 16, act="relu", name="h1")
    h1d = nn.dropout(h1, 0.3, name="h1_drop")
    h2 = nn.fc([x, h1d], 8, act="tanh", name="h2")
    out = nn.fc(h2, 3, act="softmax", name="out")
    lbl = nn.data("label", size=3, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"x": ("dense", (12,)),
                                         "label": ("label",)})


def lstm_textclf_net():
    words = nn.data("words", size=0, is_seq=True, dtype="int32")
    emb = nn.embedding(words, 12, vocab_size=40, name="emb")
    lstm = nn.lstmemory(emb, 10, name="lstm")
    pooled = nn.pooling(lstm, pooling_type="max", name="pooled")
    out = nn.fc(pooled, 3, act="softmax", name="out")
    lbl = nn.data("label", size=3, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"words": ("ids_seq", (6, 40)),
                                         "label": ("label",)})


def gru_crf_tagger_net():
    words = nn.data("words", size=0, is_seq=True, dtype="int32")
    emb = nn.embedding(words, 10, vocab_size=30, name="emb")
    gru = nn.grumemory(emb, 8, reverse=True, name="gru")
    feat = nn.fc(gru, 5, act="linear", name="feat")
    labels = nn.data("labels", size=5, is_seq=True, dtype="int32")
    cost = nn.crf_cost(feat, labels, name="cost")
    return nn.Topology(cost), _cls_feed({"words": ("ids_seq", (6, 30)),
                                         "labels": ("labels_seq", (6, 5))})


def bidi_lstm_net():
    words = nn.data("words", size=0, is_seq=True, dtype="int32")
    emb = nn.embedding(words, 8, vocab_size=25, name="emb")
    bi = networks.bidirectional_lstm(emb, 6, name="bi")
    last = nn.last_seq(bi, name="last")
    out = nn.fc(last, 2, act="softmax", name="out")
    lbl = nn.data("label", size=2, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"words": ("ids_seq", (5, 25)),
                                         "label": ("label",)}, n_cls=2)


def text_conv_net():
    words = nn.data("words", size=0, is_seq=True, dtype="int32")
    emb = nn.embedding(words, 8, vocab_size=30, name="emb")
    conv = networks.sequence_conv_pool(emb, context_len=3, hidden_size=12,
                                       name="tconv")
    out = nn.fc(conv, 2, act="softmax", name="out")
    lbl = nn.data("label", size=2, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"words": ("ids_seq", (7, 30)),
                                         "label": ("label",)}, n_cls=2)


def mixed_projections_net():
    a = nn.data("a", size=8)
    b = nn.data("b", size=6)
    ids = nn.data("ids", size=20, dtype="int32")
    m = nn.mixed(6, input=[
        nn.full_matrix_projection(a),
        nn.trans_full_matrix_projection(b, size=6),
        nn.identity_projection(a, offset=2, size=6),
        nn.table_projection(ids, size=6),
        nn.dotmul_projection(b),
        nn.scaling_projection(b),
    ], act="tanh", bias_attr=True, name="m")
    out = nn.fc(m, 2, act="softmax", name="out")
    lbl = nn.data("label", size=2, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"a": ("dense", (8,)),
                                         "b": ("dense", (6,)),
                                         "ids": ("int", 20),
                                         "label": ("label",)}, n_cls=2)


def mixed_context_net():
    seq = nn.data("seq", size=5, is_seq=True)
    m = nn.mixed(15, input=[
        nn.context_projection_input(seq, context_len=3),
    ], name="ctx_m")
    fc = nn.fc(m, 4, act="tanh", name="fc")
    pooled = nn.pooling(fc, pooling_type="avg", name="pooled")
    tgt = nn.data("tgt", size=4)
    cost = nn.mse_cost(pooled, tgt, name="cost")
    return nn.Topology(cost), _cls_feed({"seq": ("seq", (6, 5)),
                                         "tgt": ("dense", (4,))})


def mixed_conv_net():
    img = nn.data("img", size=2, height=8, width=8)
    m = nn.mixed(input=[
        nn.conv_projection(img, filter_size=3, num_filters=4, padding=1),
    ], act="relu", name="conv_m")
    pool = nn.img_pool(m, pool_size=2, stride=2, name="pool")
    out = nn.fc(pool, 2, act="softmax", name="out")
    lbl = nn.data("label", size=2, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"img": ("dense", (8, 8, 2)),
                                         "label": ("label",)}, n_cls=2)


def recommender_net():
    uid = nn.data("uid", size=30, dtype="int32")
    mid = nn.data("mid", size=40, dtype="int32")
    ue = nn.embedding(uid, 8, name="ue")
    me = nn.embedding(mid, 8, name="me")
    uf = nn.fc(ue, 10, act="tanh", name="uf")
    mf = nn.fc(me, 10, act="tanh", name="mf")
    sim = nn.cos_sim(uf, mf, scale=5.0, name="sim")
    score = nn.data("score", size=1)
    cost = nn.mse_cost(sim, score, name="cost")
    return nn.Topology(cost), _cls_feed({"uid": ("int", 30),
                                         "mid": ("int", 40),
                                         "score": ("dense", (1,))})


def ctc_net():
    feats = nn.data("feats", size=6, is_seq=True)
    lstm = nn.lstmemory(feats, 8, name="lstm")
    logits = nn.fc(lstm, 7, act="linear", name="logits")  # 6 labels + blank
    labels = nn.data("labels", size=6, is_seq=True, dtype="int32")
    cost = nn.ctc_cost(logits, labels, name="cost")
    return nn.Topology(cost), _cls_feed({
        "feats": ("seq", (8, 6)),
        "labels": ("labels_seq", (3, 5)),
    })


def nce_net():
    words = nn.data("words", size=0, is_seq=True, dtype="int32")
    emb = nn.embedding(words, 10, vocab_size=50, name="emb")
    hid = nn.pooling(emb, pooling_type="avg", name="hid")
    lbl = nn.data("label", size=50, dtype="int32")
    cost = nn.nce_cost(hid, lbl, num_classes=50, num_neg_samples=5,
                       name="cost")
    return nn.Topology(cost), _cls_feed({"words": ("ids_seq", (4, 50)),
                                         "label": ("label",)}, n_cls=50)


def hsigmoid_net():
    x = nn.data("x", size=12)
    hid = nn.fc(x, 10, act="tanh", name="hid")
    lbl = nn.data("label", size=30, dtype="int32")
    cost = nn.hsigmoid_cost(hid, lbl, num_classes=30, name="cost")
    return nn.Topology(cost), _cls_feed({"x": ("dense", (12,)),
                                         "label": ("label",)}, n_cls=30)


def image_misc_net():
    img = nn.data("img", size=3, height=12, width=12)
    conv = nn.img_conv(img, filter_size=3, num_filters=8, padding=1,
                       name="conv")
    norm = nn.img_cmrnorm(conv, size=5, name="norm")
    mo = nn.maxout(norm, groups=2, name="mo")
    pool = nn.img_pool(mo, pool_size=2, stride=2, name="pool")
    out = nn.fc(pool, 4, act="softmax", name="out")
    lbl = nn.data("label", size=4, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"img": ("dense", (12, 12, 3)),
                                         "label": ("label",)}, n_cls=4)


def fused_inception_net():
    """Fused-reduce inception block: merged 1x1 conv + slice_channels —
    locks the new slice layer's serialization."""
    img = nn.data("img", size=8, height=8, width=8)
    red = nn.img_conv(img, filter_size=1, num_filters=8, padding=0,
                      name="red")
    b1 = nn.slice_channels(red, 0, 3, name="s1")
    b3 = nn.img_conv(nn.slice_channels(red, 3, 8, name="s3"),
                     filter_size=3, num_filters=6, padding=1, name="c3")
    cat = nn.concat([b1, b3], name="cat")
    out = nn.fc(cat, 4, act="softmax", name="out")
    lbl = nn.data("label", size=4, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"img": ("dense", (8, 8, 8)),
                                         "label": ("label",)}, n_cls=4)


def resnet_block_net():
    img = nn.data("img", size=4, height=8, width=8)
    c1 = nn.img_conv(img, filter_size=3, num_filters=4, padding=1,
                     act="linear", name="c1")
    b1 = nn.batch_norm(c1, act="relu", name="b1")
    c2 = nn.img_conv(b1, filter_size=3, num_filters=4, padding=1,
                     act="linear", name="c2")
    b2 = nn.batch_norm(c2, act="linear", name="b2")
    res = nn.addto([b2, img], act="relu", name="res")
    out = nn.fc(res, 3, act="softmax", name="out")
    lbl = nn.data("label", size=3, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"img": ("dense", (8, 8, 4)),
                                         "label": ("label",)})


def lstm_group_net():
    x = nn.data("x", size=5, is_seq=True)
    proj = nn.fc(x, 16, act="linear", bias_attr=False, name="proj")  # 4H
    grp = networks.lstmemory_group(proj, 4, name="lg")
    last = nn.last_seq(grp, name="last")
    tgt = nn.data("tgt", size=4)
    cost = nn.mse_cost(last, tgt, name="cost")
    return nn.Topology(cost), _cls_feed({"x": ("seq", (5, 5)),
                                         "tgt": ("dense", (4,))})


def gru_group_net():
    x = nn.data("x", size=4, is_seq=True)
    proj = nn.fc(x, 9, act="linear", bias_attr=False, name="proj")  # 3H
    grp = networks.gru_group(proj, 3, reverse=True, name="gg")
    first = nn.first_seq(grp, name="first")
    tgt = nn.data("tgt", size=3)
    cost = nn.mse_cost(first, tgt, name="cost")
    return nn.Topology(cost), _cls_feed({"x": ("seq", (5, 4)),
                                         "tgt": ("dense", (3,))})


def simple_gru2_net():
    x = nn.data("x", size=6, is_seq=True)
    g = networks.simple_gru2(x, 5, name="sg")
    pooled = nn.pooling(g, pooling_type="max", name="pooled")
    out = nn.fc(pooled, 2, act="softmax", name="out")
    lbl = nn.data("label", size=2, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"x": ("seq", (5, 6)),
                                         "label": ("label",)}, n_cls=2)


def db_lstm_style_net():
    w = nn.data("w", size=0, is_seq=True, dtype="int32")
    c = nn.data("c", size=0, is_seq=True, dtype="int32")
    shared = nn.ParamAttr(name="emb")
    e1 = nn.embedding(w, 6, vocab_size=20, param_attr=shared, name="e1")
    e2 = nn.embedding(c, 6, vocab_size=20, param_attr=shared, name="e2")
    hidden = nn.mixed(16, input=[nn.full_matrix_projection(e1),
                                 nn.full_matrix_projection(e2)],
                      bias_attr=True, name="hidden")
    lstm = nn.lstmemory(hidden, projected_input=True, act="relu",
                        state_act="sigmoid", name="lstm")
    feat = nn.mixed(5, input=[nn.full_matrix_projection(hidden),
                              nn.full_matrix_projection(lstm)],
                    bias_attr=True, name="feat")
    labels = nn.data("labels", size=5, is_seq=True, dtype="int32")
    cost = nn.crf_cost(feat, labels, name="cost")
    return nn.Topology(cost), _cls_feed({"w": ("ids_seq", (6, 20)),
                                         "c": ("ids_seq", (6, 20)),
                                         "labels": ("labels_seq", (6, 5))})


def seq_ops_net():
    x = nn.data("x", size=4, is_seq=True)
    y = nn.data("y", size=4, is_seq=True)
    rev = nn.seq_reverse(x, name="rev")
    cat = nn.seq_concat(rev, y, name="cat")
    first = nn.first_seq(cat, name="first")
    expanded = nn.expand(first, cat, name="expanded")
    pooled = nn.pooling(expanded, pooling_type="sum", name="pooled")
    tgt = nn.data("tgt", size=4)
    cost = nn.mse_cost(pooled, tgt, name="cost")
    return nn.Topology(cost), _cls_feed({"x": ("seq", (4, 4)),
                                         "y": ("seq", (4, 4)),
                                         "tgt": ("dense", (4,))})


def selective_fc_net():
    x = nn.data("x", size=8)
    sel = nn.data("sel", size=20)  # dense 0/1 selection (mask mode)
    out = nn.selective_fc(x, sel, size=20, act="linear", name="sfc")
    tgt = nn.data("label", size=20, dtype="int32")
    cost = nn.classification_cost(input=out, label=tgt, name="cost")

    def feed(rng):
        return {
            "x": rng.rand(2, 8).astype(np.float32),
            "sel": (rng.rand(2, 20) > 0.5).astype(np.float32),
            "label": rng.randint(0, 20, (2, 1)).astype(np.int32),
        }

    return nn.Topology(cost), feed


def vgg_block_net():
    img = nn.data("img", size=3, height=8, width=8)
    blk = networks.img_conv_group(img, [4, 4], conv_batchnorm=True,
                                  conv_batchnorm_drop_rate=[0.3, 0],
                                  pool_size=2, pool_stride=2, name="blk")
    out = nn.fc(blk, 3, act="softmax", name="out")
    lbl = nn.data("label", size=3, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost), _cls_feed({"img": ("dense", (8, 8, 3)),
                                         "label": ("label",)})


def rank_cost_net():
    a = nn.data("a", size=6)
    b = nn.data("b", size=6)
    sa = nn.fc(a, 1, act="linear", name="sa")
    sb = nn.fc(b, 1, act="linear", name="sb")
    lbl = nn.data("label", size=1)
    cost = nn.rank_cost(sa, sb, lbl, name="cost")

    def feed(rng):
        return {"a": rng.rand(2, 6).astype(np.float32),
                "b": rng.rand(2, 6).astype(np.float32),
                "label": rng.randint(0, 2, (2, 1)).astype(np.float32)}

    return nn.Topology(cost), feed


#: name -> builder; the golden file is tests/golden/<name>.protostr
GOLDEN_NETS = {
    "fc_dropout": fc_dropout_net,
    "lstm_textclf": lstm_textclf_net,
    "gru_crf_tagger": gru_crf_tagger_net,
    "bidi_lstm": bidi_lstm_net,
    "text_conv": text_conv_net,
    "mixed_projections": mixed_projections_net,
    "mixed_context": mixed_context_net,
    "mixed_conv": mixed_conv_net,
    "recommender": recommender_net,
    "ctc": ctc_net,
    "nce": nce_net,
    "hsigmoid": hsigmoid_net,
    "image_misc": image_misc_net,
    "fused_inception": fused_inception_net,
    "resnet_block": resnet_block_net,
    "lstm_group": lstm_group_net,
    "gru_group": gru_group_net,
    "simple_gru2": simple_gru2_net,
    "db_lstm_style": db_lstm_style_net,
    "seq_ops": seq_ops_net,
    "selective_fc": selective_fc_net,
    "vgg_block": vgg_block_net,
    "rank_cost": rank_cost_net,
}
