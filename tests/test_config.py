"""Config serialization tests — the protostr golden-file analog.

Reference test strategy: configs are parsed and the resulting protostr is
compared to checked-in `.protostr` files
(python/paddle/trainer_config_helpers/tests); C++ rebuilds networks from the
proto and training proceeds identically (TrainerConfigHelper.cpp:33-54).
Here: dump a Topology to ModelConfig, compare deterministic text to a golden
file, rebuild from the proto, and check the rebuilt graph computes identical
outputs with the same parameters. Plus deploy-bundle (MergeModel analog)
roundtrips.
"""

import os

import jax
import numpy as np
import pytest

import paddle_tpu.nn as nn
from paddle_tpu.config import (
    SerializationError,
    build_optimizer,
    build_topology,
    dump_model_config,
    dump_trainer_config,
    load_inference_model,
    merge_model,
    parse_protostr,
    protostr,
)
from paddle_tpu.param.optimizers import Adam
from paddle_tpu.utils.error import ConfigError

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


def _simple_net():
    nn.reset_naming()
    img = nn.data("img", size=1, height=8, width=8)
    conv = nn.img_conv(img, filter_size=3, num_filters=4, act="relu", name="conv1")
    pool = nn.img_pool(conv, pool_size=2, stride=2, name="pool1")
    h = nn.fc(pool, 32, act="tanh", name="hidden")
    out = nn.fc(h, 10, act="softmax", name="output")
    lbl = nn.data("label", size=10, dtype="int32")
    cost = nn.classification_cost(input=out, label=lbl, name="cost")
    return nn.Topology(cost)


def _seq_net():
    nn.reset_naming()
    words = nn.data("words", size=0, is_seq=True, dtype="int32")
    emb = nn.embedding(words, 16, vocab_size=50, name="emb")
    lstm = nn.lstmemory(emb, 24, name="lstm")
    agg = nn.last_seq(lstm, name="agg")
    out = nn.fc(agg, 3, act="softmax", name="out")
    lbl = nn.data("label", size=3, dtype="int32")
    return nn.Topology(nn.classification_cost(input=out, label=lbl, name="cost"))


def test_golden_protostr():
    topo = _simple_net()
    mc = dump_model_config(topo, "simple_net")
    # normalize run-environment fields so the golden only captures the
    # config format itself (version bumps / dtype flags are not regressions)
    mc.framework_version = ""
    mc.dtype_policy = ""
    text = protostr(mc)
    path = os.path.join(GOLDEN_DIR, "simple_net.protostr")
    assert os.path.exists(path), (
        "golden file missing — regenerate deliberately with "
        "tests/golden/regen.py and review the diff"
    )
    with open(path) as f:
        golden = f.read()
    assert text == golden, "ModelConfig text changed vs golden file"


def test_protostr_parse_roundtrip():
    mc = dump_model_config(_simple_net(), "simple_net")
    mc2 = parse_protostr(protostr(mc))
    assert mc2 == mc


@pytest.mark.parametrize("make", [_simple_net, _seq_net])
def test_rebuild_equivalence(make, rng):
    topo = make()
    mc = dump_model_config(topo)
    topo2 = build_topology(mc)
    assert [l.name for l in topo2.layers] == [l.name for l in topo.layers]
    assert {n: s.shape for n, s in topo2.param_specs.items()} == {
        n: s.shape for n, s in topo.param_specs.items()
    }
    params, state = topo.init(jax.random.PRNGKey(0))
    if "img" in [l.name for l in topo.data_layers]:
        feed = {
            "img": rng.rand(2, 8, 8, 1).astype("float32"),
            "label": np.array([1, 2]),
        }
    else:
        feed = {
            "words": (rng.randint(0, 50, (2, 5)), np.array([5, 3])),
            "label": np.array([0, 2]),
        }
    o1, _ = topo.apply(params, state, feed)
    o2, _ = topo2.apply(params, state, feed)
    cost1 = np.asarray(o1["cost"].value)
    cost2 = np.asarray(o2["cost"].value)
    np.testing.assert_allclose(cost1, cost2, rtol=1e-6)


def test_unserializable_graph_raises():
    nn.reset_naming()
    x = nn.data("x", size=4)
    # a hand-built LayerOutput (no recorded constructor) must be rejected
    from paddle_tpu.nn.graph import Act, LayerOutput

    node = LayerOutput("custom", "custom", 4, [x], lambda ctx, p, a: a)
    with pytest.raises(SerializationError):
        dump_model_config(nn.Topology(node))


def test_trainer_config_optimizer_roundtrip():
    topo = _simple_net()
    opt = Adam(learning_rate=3e-4, beta1=0.85)
    opt.learning_rate_schedule = "poly"
    opt.schedule_args = {"decay_a": 1e-3}
    tc = dump_trainer_config(topo, opt, batch_size=32, num_passes=2, seed=7)
    assert tc.batch_size == 32 and tc.model.name == "model"
    opt2 = build_optimizer(tc.optimizer)
    assert type(opt2) is Adam
    assert opt2.learning_rate == pytest.approx(3e-4)
    assert opt2.beta1 == pytest.approx(0.85)
    assert opt2.learning_rate_schedule == "poly"
    assert float(opt2.lr_at(100)) == pytest.approx(float(opt.lr_at(100)))


def test_merge_model_bundle(tmp_path, rng):
    topo = _seq_net()
    params, state = topo.init(jax.random.PRNGKey(1))
    path = str(tmp_path / "model.ptz")
    merge_model(path, topo, params, state, name="textclf")
    m = load_inference_model(path)
    assert m.input_names == ["words", "label"] or set(m.input_names) == {
        "words",
        "label",
    }
    feed = {
        "words": (rng.randint(0, 50, (2, 5)), np.array([5, 3])),
        "label": np.array([0, 2]),
    }
    got = m.infer(feed, outputs=["out"])
    want, _ = topo.apply(params, state, feed, outputs=["out"])
    np.testing.assert_allclose(
        got["out"], np.asarray(want["out"].value), rtol=1e-5, atol=1e-6
    )


def test_typed_fields_present_and_validated():
    """Typed layer fields (the ModelConfig.proto contract analog) are written
    for the top families, old bundles without them still load, and a
    tampered typed field is rejected."""
    mc = dump_model_config(_simple_net(), "m")
    by_type = {}
    for lc in mc.layers:
        w = lc.WhichOneof("typed")
        if w:
            by_type.setdefault(w, lc)
    assert "fc" in by_type and by_type["fc"].fc.size > 0
    assert "cost" in by_type

    # old-bundle compatibility: strip typed fields -> still rebuilds
    mc_old = type(mc)()
    mc_old.CopyFrom(mc)
    for lc in mc_old.layers:
        if lc.WhichOneof("typed"):
            lc.ClearField(lc.WhichOneof("typed"))
    topo = build_topology(mc_old)
    assert topo.output_names() == list(mc.output_layer_names)

    # tampered typed field -> schema validation error
    mc_bad = type(mc)()
    mc_bad.CopyFrom(mc)
    for lc in mc_bad.layers:
        if lc.WhichOneof("typed") == "fc":
            lc.fc.size = lc.fc.size + 1
            break
    with pytest.raises(ConfigError, match="typed fc.size"):
        build_topology(mc_bad)
