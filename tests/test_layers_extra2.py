"""Long-tail layer inventory (layers_extra2) vs brute-force references —
the per-layer numeric-check pattern of the reference's test_LayerGrad."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.nn as nn
from paddle_tpu.nn.graph import Act


@pytest.fixture(autouse=True)
def fresh_names():
    nn.reset_naming()
    yield


def _run(out, feed, train=False):
    topo = nn.Topology(out)
    params, state = topo.init(jax.random.PRNGKey(0))
    outs, new_state = topo.apply(params, state, feed, train=train)
    return outs[out.name], params, new_state


def test_prelu(rng):
    x = nn.data("x", size=6)
    out = nn.prelu(x, name="p")
    topo = nn.Topology(out)
    params, state = topo.init(jax.random.PRNGKey(0))
    params["_p.w0"] = jnp.full((6,), 0.25)
    xv = rng.randn(4, 6).astype(np.float32)
    o, _ = topo.apply(params, state, {"x": xv})
    want = np.maximum(xv, 0) + 0.25 * np.minimum(xv, 0)
    np.testing.assert_allclose(np.asarray(o[out.name].value), want, rtol=1e-6)


def test_trans_and_resize(rng):
    x = nn.data("x", size=12)
    t = nn.trans(nn.resize(x, 9, name="r"), name="t")  # 12*3 -> rows of 9 (3x3)
    xv = rng.randn(3, 12).astype(np.float32)
    got, _, _ = _run(t, {"x": xv})
    want = xv.reshape(4, 9).reshape(4, 3, 3).transpose(0, 2, 1).reshape(4, 9)
    np.testing.assert_allclose(np.asarray(got.value), want, rtol=1e-6)


def test_data_norm_zscore(rng):
    x = nn.data("x", size=5)
    out = nn.data_norm(x, name="dn")
    xv = (rng.randn(64, 5) * 3 + 7).astype(np.float32)
    got, params, new_state = _run(out, {"x": xv}, train=True)
    v = np.asarray(got.value)
    np.testing.assert_allclose(v.mean(0), 0, atol=1e-4)
    np.testing.assert_allclose(v.std(0), 1, atol=1e-2)
    assert float(np.abs(np.asarray(new_state["_dn.mean"])).max()) > 0  # stats updated


def test_conv_shift(rng):
    a = nn.data("a", size=8)
    b = nn.data("b", size=3)
    out = nn.conv_shift(a, b)
    av = rng.randn(2, 8).astype(np.float32)
    bv = rng.randn(2, 3).astype(np.float32)
    got, _, _ = _run(out, {"a": av, "b": bv})
    want = np.zeros_like(av)
    for bi in range(2):
        for i in range(8):
            for j in range(3):
                want[bi, i] += bv[bi, j] * av[bi, (i + j - 1) % 8]
    np.testing.assert_allclose(np.asarray(got.value), want, rtol=1e-5, atol=1e-6)


def test_linear_comb_and_cos_vm(rng):
    w = nn.data("w", size=3)
    m = nn.data("m", size=12)
    v = nn.data("v", size=4)
    lc = nn.linear_comb(w, m, 4)
    cv = nn.cos_vm(v, m)
    wv = rng.randn(2, 3).astype(np.float32)
    mv = rng.randn(2, 12).astype(np.float32)
    vv = rng.randn(2, 4).astype(np.float32)
    got, _, _ = _run(lc, {"w": wv, "m": mv})
    want = np.einsum("bk,bkd->bd", wv, mv.reshape(2, 3, 4))
    np.testing.assert_allclose(np.asarray(got.value), want, rtol=1e-5)
    got2, _, _ = _run(cv, {"v": vv, "m": mv})
    mm = mv.reshape(2, 3, 4)
    want2 = np.einsum("bd,bkd->bk", vv, mm) / (
        np.linalg.norm(vv, axis=1, keepdims=True) * np.linalg.norm(mm, axis=2) + 1e-8)
    np.testing.assert_allclose(np.asarray(got2.value), want2, rtol=1e-4)


def test_get_output_lstm_cell_state(rng):
    x = nn.data("x", size=0, is_seq=True, dtype="int32")
    emb = nn.embedding(x, 8, vocab_size=20)
    lstm = nn.lstmemory(emb, 6, name="l")
    # lstmemory exposes final states via Act.state
    topo_probe = nn.Topology(lstm)
    p, s = topo_probe.init(jax.random.PRNGKey(0))
    feed = {"x": (rng.randint(0, 20, (2, 5)), np.array([5, 3]))}
    acts, _ = topo_probe.apply(p, s, feed)
    keys = sorted(acts[lstm.name].state)
    assert keys, "lstmemory exposes no aux state"
    out = nn.get_output(lstm, keys[0])
    got, _, _ = _run(out, feed)
    assert np.asarray(got.value).shape[0] == 2


def test_lambda_cost_prefers_correct_ranking(rng):
    s = nn.data("s", size=1, is_seq=True)
    l = nn.data("l", size=1, is_seq=True)
    out = nn.lambda_cost(s, l, NDCG_num=3)
    rel = np.array([[3.0, 2.0, 1.0, 0.0]], np.float32)[..., None]
    lens = np.array([4])
    good = np.array([[4.0, 3.0, 2.0, 1.0]], np.float32)[..., None]
    bad = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)[..., None]
    c_good, _, _ = _run(out, {"s": (good, lens), "l": (rel, lens)})
    nn.reset_naming()
    s2 = nn.data("s", size=1, is_seq=True)
    l2 = nn.data("l", size=1, is_seq=True)
    out2 = nn.lambda_cost(s2, l2, NDCG_num=3)
    c_bad, _, _ = _run(out2, {"s": (bad, lens), "l": (rel, lens)})
    assert float(c_good.value) < float(c_bad.value)


def test_selective_fc(rng):
    x = nn.data("x", size=5)
    sel = nn.data("sel", size=7)
    out = nn.selective_fc(x, sel, 7, act="linear", name="sfc")
    xv = rng.randn(3, 5).astype(np.float32)
    sv = (rng.rand(3, 7) > 0.5).astype(np.float32)
    got, params, _ = _run(out, {"x": xv, "sel": sv})
    v = np.asarray(got.value)
    assert np.all(v[sv == 0] == 0)
    dense = xv @ np.asarray(params["_sfc.w0"]) + np.asarray(params["_sfc.wbias"])
    np.testing.assert_allclose(v[sv == 1], dense[sv == 1], rtol=1e-4, atol=1e-5)


def test_spp_fixed_size(rng):
    img = nn.data("img", size=3, height=7, width=5)
    out = nn.spp(img, pyramid_height=3)
    assert out.size == 3 * (1 + 4 + 16)
    xv = rng.rand(2, 7, 5, 3).astype(np.float32)
    got, _, _ = _run(out, {"img": xv})
    assert np.asarray(got.value).shape == (2, out.size)
    # the 1x1 bin is the global max
    np.testing.assert_allclose(np.asarray(got.value)[:, :3],
                               xv.max(axis=(1, 2)), rtol=1e-6)


def test_priorbox_shapes_and_bounds(rng):
    img = nn.data("img", size=3, height=32, width=32)
    feat = nn.img_pool(nn.img_conv(img, filter_size=3, num_filters=4),
                       pool_size=4, stride=4)
    pb = nn.priorbox(feat, img, min_size=[10], max_size=[20],
                     aspect_ratio=[2.0])
    got, _, _ = _run(pb, {"img": rng.rand(1, 32, 32, 3).astype(np.float32)})
    v = np.asarray(got.value)
    assert v.shape == (1, 2, pb.size)
    assert v[0, 0].min() >= 0.0 and v[0, 0].max() <= 1.0


def test_eos_id(rng):
    x = nn.data("x", size=0, is_seq=True, dtype="int32")
    out = nn.eos_id(x, eos_id=1)
    ids = np.array([[3, 1, 4, 1], [1, 5, 6, 7]], np.int32)
    got, _, _ = _run(out, {"x": (ids, np.array([4, 2]))})
    v = np.asarray(got.value)
    np.testing.assert_array_equal(v, [[0, 1, 0, 1], [1, 0, 0, 0]])


def test_img_conv_transpose_upsamples(rng):
    img = nn.data("img", size=2, height=4, width=4)
    out = nn.img_conv_transpose(img, filter_size=3, num_filters=5, stride=2)
    assert out.meta["hw"] == (8, 8)
    got, _, _ = _run(out, {"img": rng.rand(2, 4, 4, 2).astype(np.float32)})
    assert np.asarray(got.value).shape == (2, 8, 8, 5)


def test_mdlstm_matches_python_loop(rng):
    img = nn.data("img", size=3, height=3, width=4)
    out = nn.mdlstmemory(img, 5, name="md")
    topo = nn.Topology(out)
    params, state = topo.init(jax.random.PRNGKey(1))
    xv = rng.randn(2, 3, 4, 3).astype(np.float32) * 0.5
    got, _ = topo.apply(params, state, {"img": xv})
    v = np.asarray(got[out.name].value)
    assert v.shape == (2, 3, 4, 5)

    # brute-force python loop with the same params
    wx = np.asarray(params["_md.wx"]); wl = np.asarray(params["_md.wl"])
    wt = np.asarray(params["_md.wt"]); b = np.asarray(params["_md.wbias"])
    H = 5

    def sig(a):
        return 1 / (1 + np.exp(-a))

    hs = np.zeros((2, 3, 4, H)); cs = np.zeros((2, 3, 4, H))
    for i in range(3):
        for j in range(4):
            h_left = hs[:, i, j - 1] if j > 0 else np.zeros((2, H))
            c_left = cs[:, i, j - 1] if j > 0 else np.zeros((2, H))
            h_top = hs[:, i - 1, j] if i > 0 else np.zeros((2, H))
            c_top = cs[:, i - 1, j] if i > 0 else np.zeros((2, H))
            z = xv[:, i, j] @ wx + b + h_left @ wl + h_top @ wt
            ii, fl, ft, o, g = np.split(z, 5, axis=-1)
            c = sig(fl) * c_left + sig(ft) * c_top + sig(ii) * np.tanh(g)
            hs[:, i, j] = sig(o) * np.tanh(c)
            cs[:, i, j] = c
    np.testing.assert_allclose(v, hs, rtol=1e-4, atol=1e-5)


def test_extra2_layers_serialize(rng):
    """New constructors round-trip through ModelConfig."""
    from paddle_tpu.config import build_topology, dump_model_config

    x = nn.data("x", size=6)
    out = nn.prelu(nn.fc(x, 6, name="h"), name="pr")
    topo = nn.Topology(out)
    topo2 = build_topology(dump_model_config(topo))
    params, state = topo.init(jax.random.PRNGKey(0))
    feed = {"x": rng.randn(2, 6).astype(np.float32)}
    o1, _ = topo.apply(params, state, feed)
    o2, _ = topo2.apply(params, state, feed)
    np.testing.assert_allclose(np.asarray(o1["pr"].value),
                               np.asarray(o2["pr"].value), rtol=1e-6)


def test_selective_fc_multi_input(rng):
    """Multiple inputs get separate weights summed, like fc
    (SelectiveFullyConnectedLayer.cpp iterates all inputs)."""
    nn.reset_naming()
    a = nn.data("a", size=5)
    b = nn.data("b", size=3)
    sel = nn.data("sel", size=7)
    out = nn.selective_fc([a, b], sel, 7, act="linear", name="sfc")
    av = rng.randn(4, 5).astype(np.float32)
    bv = rng.randn(4, 3).astype(np.float32)
    sv = (rng.rand(4, 7) > 0.5).astype(np.float32)
    got, params, _ = _run(out, {"a": av, "b": bv, "sel": sv})
    v = np.asarray(got.value)
    dense = (av @ np.asarray(params["_sfc.w0"])
             + bv @ np.asarray(params["_sfc.w1"])
             + np.asarray(params["_sfc.wbias"]))
    assert np.all(v[sv == 0] == 0)
    np.testing.assert_allclose(v[sv == 1], dense[sv == 1], rtol=1e-4, atol=1e-5)


def test_error_clip_identity_forward_clipped_backward():
    """error_clip: identity forward; backward error clipped to threshold
    (ExtraLayerAttribute.error_clipping_threshold analog)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu.nn as nn

    nn.reset_naming()
    x = nn.data("x", size=3)
    clipped = nn.error_clip(x, 0.1)
    out = nn.fc(clipped, 1, act="linear", name="head",
                param_attr=nn.ParamAttr(initial_std=0.0))
    topo = nn.Topology([out])
    params, state = topo.init(jax.random.PRNGKey(0))
    params = {k: jnp.ones_like(v) * 5.0 for k, v in params.items()}

    xv = jnp.asarray(np.ones((2, 3), np.float32))
    outs, _ = topo.apply(params, state, {"x": xv}, train=False)
    np.testing.assert_allclose(np.asarray(outs[out.name].value),
                               np.asarray((xv @ (5.0 * np.ones((3, 1)))) + 5.0))

    # grad wrt input flows through fc (weight 5.0) then gets clipped to 0.1
    def loss(xv):
        outs, _ = topo.apply(params, state, {"x": xv}, train=False)
        return jnp.sum(outs[out.name].value)

    g = jax.grad(loss)(xv)
    np.testing.assert_allclose(np.asarray(g), 0.1 * np.ones((2, 3)), rtol=1e-6)


def test_img_pool_int_padding_ceil_mode():
    """pool3s2p1 on 28px: reference ceil semantics give 15 (floor gives 14);
    extra bottom/right padding keeps the last window in place (ADVICE r2)."""
    import jax

    nn.reset_naming()
    img = nn.data("img", size=2, height=28, width=28)
    pc = nn.img_pool(img, pool_size=3, stride=2, padding=1, name="ceil")
    pf = nn.img_pool(img, pool_size=3, stride=2, padding=1, ceil_mode=False,
                     name="floor")
    assert pc.meta["hw"] == (15, 15)
    assert pf.meta["hw"] == (14, 14)
    topo = nn.Topology([pc, pf])
    params, state = topo.init(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).randn(2, 28, 28, 2).astype(np.float32)
    out, _ = topo.apply(params, state, {"img": x})
    assert out["ceil"].value.shape == (2, 15, 15, 2)
    assert out["floor"].value.shape == (2, 14, 14, 2)
    # interior windows agree between the two modes
    np.testing.assert_allclose(np.asarray(out["ceil"].value)[:, :14, :14],
                               np.asarray(out["floor"].value), rtol=1e-6)


def test_img_pool_ceil_clips_all_padding_window():
    """pool2s2p1 on 5px: naive ceil gives 4 but the 4th window starts wholly
    in padding -> -inf/NaN; the legacy clip drops it (output 3)."""
    import jax

    nn.reset_naming()
    img = nn.data("img", size=1, height=5, width=5)
    pm = nn.img_pool(img, pool_size=2, stride=2, padding=1, name="mx")
    pa = nn.img_pool(img, pool_size=2, stride=2, padding=1, pool_type="avg",
                     name="av")
    assert pm.meta["hw"] == (3, 3)
    topo = nn.Topology([pm, pa])
    params, state = topo.init(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).randn(2, 5, 5, 1).astype(np.float32)
    out, _ = topo.apply(params, state, {"img": x})
    for nm in ("mx", "av"):
        v = np.asarray(out[nm].value)
        assert v.shape == (2, 3, 3, 1)
        assert np.isfinite(v).all(), nm
