"""Checkpoint resharding across an elastic world-size change.

The elastic gang (docs/resilience.md) shrinks or grows the device world by
re-instantiating ONE ``MeshConfig``; the checkpoint needs no translation
step because arrays are stored host-side and layout-free — "resharding
falls out of the manifest".  These tests pin that contract end to end on
the 8-virtual-device CPU mesh:

- save under world=4, restore under world=2 AND world=8: dense params and
  optimizer state restore bit-identical, and the pserver tables (data,
  optimizer slots, dirty bits) are BIT-identical to a fresh same-size
  shard of the full saved state — true vocab rows carried over, tail
  re-padded with zeros to the new shard multiple;
- the manifest meta records the MeshConfig the state was saved under
  (attribution for the reshard);
- training resumed at the new world size matches a same-checkpoint resume
  at the original world;
- a corrupt checkpoint member surfaces as the typed ``CheckpointError``
  naming the failing member, not as a garbled restore.
"""

import os

import jax
import numpy as np
import pytest

import paddle_tpu.nn as nn
from paddle_tpu.param.optimizers import Adam
from paddle_tpu.parallel import MeshConfig
from paddle_tpu.resilience import CheckpointError
from paddle_tpu.resilience.checkpoint_io import pass_dir, read_manifest
from paddle_tpu.trainer import SGDTrainer
from tests.conftest import on_accelerator

pytestmark = pytest.mark.skipif(
    on_accelerator(), reason="assumes the 8-virtual-device CPU mesh")

# 50 rows never divides evenly across every world: padded vocab is 50 at
# 1–2 shards, 52 at 4, 56 at 8 — every resize below actually re-pads.
VOCAB, DIM = 50, 16
TABLE = "_u_emb.w0"


# ---------------------------------------------------------------------------
# MeshConfig: the resize/fit_world algebra (no devices needed)
# ---------------------------------------------------------------------------


def test_meshconfig_resize_and_fit_world():
    cfg = MeshConfig.of(data=4, model=2)
    assert cfg.size == 8 and cfg.axis_names == ("data", "model")
    # fit_world rescales ONLY the elastic (data) axis; order is preserved
    assert cfg.fit_world(4).shape == {"data": 2, "model": 2}
    assert cfg.fit_world(16).axes == (("data", 8), ("model", 2))
    # resize keeps unmentioned axes
    assert cfg.resize(model=4).shape == {"data": 4, "model": 4}
    # a missing axis is a size-1 axis for every query
    assert cfg.axis_size("stage") == 1
    from paddle_tpu.utils.error import ConfigError
    with pytest.raises(ConfigError, match="cannot fit"):
        cfg.fit_world(1)                   # model=2 is topology, not capacity


def test_meshconfig_resize_absent_axis_appends():
    """Regression: resizing (or fit_world-ing) an axis that is absent from
    ``axes`` must APPEND it, not crash with 'duplicate mesh axis names'."""
    cfg = MeshConfig.of(model=2)
    grown = cfg.resize(data=4)
    assert grown.axes == (("model", 2), ("data", 4))
    # fit_world on a config without its elastic axis takes the same path
    assert MeshConfig.of(model=2).fit_world(4).shape == {"model": 2,
                                                         "data": 2}


def test_meshconfig_json_roundtrip():
    cfg = MeshConfig.of(data=2, model=4).resize(model=2)
    back = MeshConfig.from_json(cfg.to_json())
    assert back == cfg and back.axes == (("data", 2), ("model", 2))


@pytest.fixture(autouse=True)
def fresh_names():
    nn.reset_naming()
    yield


def _net():
    uid = nn.data("uid", size=VOCAB, dtype="int32")
    lab = nn.data("y", size=1)
    emb = nn.embedding(uid, DIM, name="u_emb", sparse_grad=True)
    h = nn.fc(emb, 8, act="relu", name="h")
    return nn.mse_cost(nn.fc(h, 1, act="linear", name="p"), lab,
                       name="cost")


def _feeds(rng, n=3, b=16):
    return [{"uid": rng.randint(0, VOCAB, (b, 1)).astype(np.int32),
             "y": rng.randn(b, 1).astype(np.float32)} for _ in range(n)]


def _trainer(world: int, seed: int) -> SGDTrainer:
    """A trainer whose whole world is the pserver axis: ``world`` is the
    table shard count, so resizing it changes the padded vocab."""
    nn.reset_naming()
    cfg = MeshConfig.of(model=world)
    return SGDTrainer(_net(), Adam(learning_rate=0.05), seed=seed,
                      mesh=cfg)


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def _assert_resharded(new_leaf: np.ndarray, old_leaf: np.ndarray,
                      v_pad_new: int, name: str):
    """``new_leaf`` must be the fresh same-size shard of ``old_leaf``:
    identical true-vocab rows, zero tail padding, new padded length."""
    assert new_leaf.shape[0] == v_pad_new, name
    np.testing.assert_array_equal(new_leaf[:VOCAB], old_leaf[:VOCAB],
                                  err_msg=name)
    np.testing.assert_array_equal(
        new_leaf[VOCAB:], np.zeros_like(new_leaf[VOCAB:]), err_msg=name)


@pytest.mark.parametrize("world_new", [2, 8])
def test_save_world4_restore_other_world_bit_exact(rng, tmp_path,
                                                   world_new):
    t4 = _trainer(4, seed=5)
    for f in _feeds(rng):
        t4.train_batch(f)
    t4.save(str(tmp_path), 0)

    # the manifest records the world shape the state was saved under
    meta = read_manifest(pass_dir(str(tmp_path), 0))["meta"]
    assert meta["mesh"]["axes"] == [["model", 4]]

    t = _trainer(world_new, seed=99)        # different seed: nothing carries
    assert t.pserver.tables[TABLE].vocab_padded != \
        t4.pserver.tables[TABLE].vocab_padded
    t.load(str(tmp_path), 0)

    # dense params + optimizer state are layout-free: bit-identical
    for k, a in t4.params.items():
        np.testing.assert_array_equal(np.asarray(t.params[k]),
                                      np.asarray(a), err_msg=k)
    for x, y in zip(_leaves(t.opt_state), _leaves(t4.opt_state)):
        np.testing.assert_array_equal(x, y)

    # pserver table, slots, and dirty bits: fresh same-size re-shard
    v_pad = t.pserver.tables[TABLE].vocab_padded
    _assert_resharded(np.asarray(t.pserver.tables[TABLE].data),
                      np.asarray(t4.pserver.tables[TABLE].data),
                      v_pad, "table")
    old_slots, new_slots = (_leaves(x.pserver._slots[TABLE])
                            for x in (t4, t))
    assert len(old_slots) == len(new_slots)
    for i, (old, new) in enumerate(zip(old_slots, new_slots)):
        if old.ndim >= 1 and old.shape[0] == \
                t4.pserver.tables[TABLE].vocab_padded:
            _assert_resharded(new, old, v_pad, f"slot[{i}]")
        else:                                # scalar slot (e.g. step count)
            np.testing.assert_array_equal(new, old, err_msg=f"slot[{i}]")
    _assert_resharded(np.asarray(t.pserver.tables[TABLE].dirty),
                      np.asarray(t4.pserver.tables[TABLE].dirty),
                      v_pad, "dirty")
    assert np.asarray(t.pserver.tables[TABLE].dirty).any()  # real carry

    # resumed training at the new world tracks a same-checkpoint resume
    # at the ORIGINAL world (collective reduction order may differ)
    t4b = _trainer(4, seed=98)
    t4b.load(str(tmp_path), 0)
    nxt = _feeds(rng, n=2)
    for f in nxt:
        np.testing.assert_allclose(float(t.train_batch(f)),
                                   float(t4b.train_batch(f)), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(t.pserver.tables[TABLE].data)[:VOCAB],
        np.asarray(t4b.pserver.tables[TABLE].data)[:VOCAB],
        rtol=1e-6, atol=1e-7)


def test_corrupt_member_is_typed_error_naming_the_member(rng, tmp_path):
    t4 = _trainer(4, seed=7)
    t4.train_batch(_feeds(rng, n=1)[0])
    t4.save(str(tmp_path), 0)
    member = os.path.join(pass_dir(str(tmp_path), 0), "pserver.npz")
    blob = bytearray(open(member, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(member, "wb") as f:
        f.write(bytes(blob))
    t2 = _trainer(2, seed=8)
    with pytest.raises(CheckpointError, match="pserver.npz"):
        t2.load(str(tmp_path), 0)
