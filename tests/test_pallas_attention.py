"""VMEM-resident Pallas attention-decoder kernels (interpret mode on CPU)
vs the XLA scan path of ops/attention_decoder.py — forward, residuals, and
every gradient.

The Pallas path is gated to the TPU backend (attention_decoder.
_attn_pallas_block), so on CPU the gate is monkeypatched to a fixed batch
block and the kernels run through the Pallas interpreter; numerics then
mirror the scan path exactly (f32 compute policy) and the comparisons pin
the whole custom-VJP pipeline — in-kernel reverse step + post-kernel
batched weight-grad contractions — to XLA autodiff of the identical math.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops import attention_decoder as ad
from paddle_tpu.ops.attention_decoder import attention_gru_decoder
from paddle_tpu.ops.pallas_kernels import pallas_available

from test_attention_decoder import ORDER, _tols, make_args, reference

def _hw() -> bool:
    from conftest import on_accelerator

    return on_accelerator()


# The force_pallas tests run the kernels through the INTERPRETER on tiny
# non-tile-aligned shapes — on real hardware those shapes cannot lower, so
# they are CPU-only; test_aligned_shapes_real_lowering below covers the
# actual Mosaic path in hardware mode.
pytestmark = [
    pytest.mark.skipif(not pallas_available(), reason="pallas unavailable"),
]

interpret_only = pytest.mark.skipif(
    _hw(), reason="interpret-mode equivalence (non-aligned shapes); the "
    "hardware path is covered by test_aligned_shapes_real_lowering")


@pytest.fixture
def force_pallas(monkeypatch):
    """Route attention_gru_decoder through the Pallas kernels regardless of
    backend (interpret mode handles the non-tile-aligned test shapes)."""
    monkeypatch.setattr(ad, "_attn_pallas_block", lambda B, S, D, A, H2: 2)


def test_aligned_shapes_real_lowering(monkeypatch):
    """Tile-aligned shapes through the kernels on whatever backend is live:
    real Mosaic lowering in hardware mode, interpreter on CPU.  Forward and
    enc/enc_proj/att_v grads vs the scan reference."""
    monkeypatch.setattr(ad, "_attn_pallas_block", lambda B, S, D, A, H2: 8)
    args = make_args(B=16, S=8, T=5, E=32, H2=256, D=128, A=128,
                     src_lens=(8, 5, 8, 3) * 4, trg_lens=(5, 4, 5, 2) * 4)
    vals = [args[k] for k in ORDER]
    # tolerances: one notch looser than _tols() — at these wider dims the
    # fused path's split in-projection (xp_y + ctx@wx_c vs the reference's
    # single concat matmul) reassociates ~300-term dot products, so f32
    # rounding alone exceeds the tiny-shape tolerance (this is a property
    # of the decoder decomposition, not of the Pallas kernels)
    tols = _tols() if _hw() else dict(rtol=3e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(reference(*vals)),
                               np.asarray(attention_gru_decoder(*vals)),
                               **tols)

    def loss(fn, *v):
        return jnp.sum(fn(*v) ** 2)

    g_ref = jax.grad(lambda *v: loss(reference, *v),
                     argnums=(0, 2, 3, 7, 10))(*vals)
    g_new = jax.grad(lambda *v: loss(attention_gru_decoder, *v),
                     argnums=(0, 2, 3, 7, 10))(*vals)
    for a, b, nm in zip(g_ref, g_new, ("y_emb", "enc", "enc_proj",
                                       "att_v", "wh")):
        scale = np.abs(np.asarray(a, np.float64)).max() + 1e-12
        # CPU atol sits just above the interpreter's observed worst case
        # (enc_proj: 4/16384 elements at 6.2e-3, max rel diff 1.2% — f32
        # reassociation in the split in-projection, same cause as the
        # forward tolerance note above), not at a round number below it
        np.testing.assert_allclose(np.asarray(a, np.float64) / scale,
                                   np.asarray(b, np.float64) / scale,
                                   atol=8e-3 if not _hw() else 2e-2,
                                   err_msg=nm)


@interpret_only
def test_forward_matches_scan(force_pallas):
    vals = [make_args()[k] for k in ORDER]
    np.testing.assert_allclose(np.asarray(reference(*vals)),
                               np.asarray(attention_gru_decoder(*vals)),
                               **_tols())


@interpret_only
def test_residuals_match_scan_path(monkeypatch):
    """probs/ctx/s_prev streamed out of the forward kernel must equal the
    scan path's stacked residuals — the backward consumes them directly."""
    vals = [make_args()[k] for k in ORDER]
    _, res_scan = ad._decoder_fwd_scan(*vals)
    monkeypatch.setattr(ad, "_attn_pallas_block", lambda *a: 2)
    _, res_pl = ad._decoder_fwd_scan(*vals)
    for a, b, nm in zip(res_scan, res_pl, ("probs", "ctx", "s_prev")):
        assert a.dtype == b.dtype, nm
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **_tols(),
                                   err_msg=nm)


@interpret_only
@pytest.mark.parametrize("seed", [0, 1])
def test_all_gradients_match_autodiff(force_pallas, seed):
    args = make_args(seed=seed)
    vals = [args[k] for k in ORDER]
    rs = np.random.RandomState(100 + seed)
    ct = jnp.asarray(rs.randn(4, 6, 8).astype(np.float32))
    diff_idx = [0, 1, 2, 3, 6, 7, 8, 9, 10]  # everything but the masks

    def wrap(fn):
        def loss(*dv):
            full = list(vals)
            for i, ix in enumerate(diff_idx):
                full[ix] = dv[i]
            return jnp.sum(fn(*full) * ct)
        return loss

    dv = [vals[i] for i in diff_idx]
    g_ref = jax.grad(wrap(reference), argnums=tuple(range(len(dv))))(*dv)
    g_new = jax.grad(wrap(attention_gru_decoder),
                     argnums=tuple(range(len(dv))))(*dv)
    for i, (a, b) in enumerate(zip(g_ref, g_new)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **_tols(),
                                   err_msg=f"grad {ORDER[diff_idx[i]]}")


@interpret_only
def test_masked_rows_and_uneven_block(force_pallas):
    """Masked source/target tails + a block size that splits the batch (B=4,
    Bb=2): the per-block d_enc_proj/d_v accumulators must concatenate/sum
    to the scan path's values."""
    args = make_args(src_lens=(5, 2, 4, 1), trg_lens=(3, 6, 1, 5))
    vals = [args[k] for k in ORDER]

    def loss(fn, *v):
        return jnp.sum(fn(*v) ** 2)

    g_ref = jax.grad(lambda *v: loss(reference, *v),
                     argnums=(2, 3, 7))(*vals)  # enc, enc_proj, att_v
    g_new = jax.grad(lambda *v: loss(attention_gru_decoder, *v),
                     argnums=(2, 3, 7))(*vals)
    for a, b, nm in zip(g_ref, g_new, ("enc", "enc_proj", "att_v")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **_tols(),
                                   err_msg=nm)


@interpret_only
def test_bf16_operand_policy(force_pallas):
    """bf16 enc/enc_proj (the production cache dtype): kernel path stays
    finite and within bf16 tolerance of the all-f32 kernel run."""
    args = make_args(T=8, trg_lens=(8, 6, 8, 4))
    vals = [args[k] for k in ORDER]
    i_enc, i_encP = ORDER.index("enc"), ORDER.index("enc_proj")

    def loss(enc, enc_proj, cast):
        full = list(vals)
        full[i_enc] = enc.astype(jnp.bfloat16) if cast else enc
        full[i_encP] = enc_proj.astype(jnp.bfloat16) if cast else enc_proj
        return jnp.sum(attention_gru_decoder(*full) ** 2)

    g32 = jax.grad(loss, argnums=(0, 1))(args["enc"], args["enc_proj"], False)
    g16 = jax.grad(loss, argnums=(0, 1))(args["enc"], args["enc_proj"], True)
    for a, b, nm in zip(g32, g16, ("enc", "enc_proj")):
        scale = np.abs(np.asarray(a, np.float64)).max() + 1e-6
        np.testing.assert_allclose(np.asarray(a, np.float64) / scale,
                                   np.asarray(b, np.float64) / scale,
                                   atol=3e-2, err_msg=nm)


def test_gate_rejects_cpu_and_misaligned_shapes(monkeypatch):
    """The production gate must route CPU backends and non-tile-aligned
    shapes to the XLA scan path (returning None), never to a kernel that
    cannot lower."""
    from paddle_tpu.utils.flags import FLAGS

    monkeypatch.setattr(FLAGS, "use_pallas_attention", True)
    if jax.default_backend() not in ("tpu", "axon"):
        assert ad._attn_pallas_block(384, 32, 512, 512, 1024) is None
    # force the backend probe open so the alignment branches execute on
    # CPU too (otherwise the backend check short-circuits them)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert ad._attn_pallas_block(384, 32, 512, 512, 1024) == 128
    # misaligned dims can never tile
    assert ad._attn_pallas_block(384, 32, 500, 512, 1024) is None
    assert ad._attn_pallas_block(384, 30, 512, 512, 1024) is None
    # a batch with no sublane-aligned divisor
    assert ad._attn_pallas_block(7, 32, 512, 512, 1024) is None
    monkeypatch.setattr(FLAGS, "use_pallas_attention", False)
    assert ad._attn_pallas_block(384, 32, 512, 512, 1024) is None


def test_flag_off_matches_flag_on(monkeypatch):
    """Flipping use_pallas_attention must not change results (CPU: both
    sides take the scan; the on-device equivalence is pinned by the
    A/B-verified kernels + test_aligned_shapes_real_lowering)."""
    from paddle_tpu.utils.flags import FLAGS

    vals = [make_args()[k] for k in ORDER]
    monkeypatch.setattr(FLAGS, "use_pallas_attention", False)
    off = np.asarray(attention_gru_decoder(*vals))
    monkeypatch.setattr(FLAGS, "use_pallas_attention", True)
    on = np.asarray(attention_gru_decoder(*vals))
    np.testing.assert_allclose(off, on, **_tols())
