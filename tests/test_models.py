"""Model-family tests: every model builds, trains a few steps (cost decreases),
and the seq2seq beam search produces well-formed output.  The analog of the
reference's trainer/tests one-pass configs (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.data as data
import paddle_tpu.models as models
import paddle_tpu.nn as nn
import paddle_tpu.ops as O
from paddle_tpu.param.optimizers import Adam
from paddle_tpu.trainer import SGDTrainer


@pytest.fixture(autouse=True)
def fresh_names():
    nn.reset_naming()
    yield


def _first_last_costs(trainer, reader, feeder, steps=12):
    costs = []
    it = reader()
    for _ in range(steps):
        batch = next(it)
        costs.append(float(trainer.train_batch(feeder(batch))))
    return costs


def test_lenet5_learns():
    cost, logits = models.lenet5()
    trainer = SGDTrainer(cost, Adam(learning_rate=1e-3), seed=0)
    feeder = data.DataFeeder({"pixel": "dense", "label": "int"})
    reader = data.batch(data.datasets.mnist("train", n=512), 64)
    costs = _first_last_costs(trainer, reader, feeder, steps=8)
    assert costs[-1] < costs[0]
    assert np.isfinite(costs).all()


def test_smallnet_builds_and_steps():
    cost, _ = models.smallnet()
    trainer = SGDTrainer(cost, Adam(learning_rate=1e-3), seed=0)
    feeder = data.DataFeeder({"pixel": "dense", "label": "int"})
    reader = data.batch(data.datasets.cifar10("train", n=128), 32)
    costs = _first_last_costs(trainer, reader, feeder, steps=4)
    assert np.isfinite(costs).all()


def test_resnet_cifar_builds_and_steps():
    cost, _ = models.resnet_cifar(depth=8)
    trainer = SGDTrainer(cost, Adam(learning_rate=1e-3), seed=0)
    feeder = data.DataFeeder({"pixel": "dense", "label": "int"})
    reader = data.batch(data.datasets.cifar10("train", n=64), 16)
    costs = _first_last_costs(trainer, reader, feeder, steps=4)
    assert np.isfinite(costs).all()
    # BN state must have updated
    assert any("moving_mean" in k for k in trainer.state)


def test_stacked_lstm_sentiment_learns():
    vocab = 300
    cost, logits = models.stacked_lstm_net(vocab, emb_dim=16, hid_dim=24, stacked_num=3)
    trainer = SGDTrainer(cost, Adam(learning_rate=2e-3), seed=0)
    feeder = data.DataFeeder({"words": "ids_seq", "label": "int"}, max_len=64)
    reader = data.batch(data.datasets.imdb("train", vocab_size=vocab, n=256), 32)
    costs = _first_last_costs(trainer, reader, feeder, steps=8)
    assert costs[-1] < costs[0]


def test_convolution_net_builds():
    cost, _ = models.convolution_net(200, emb_dim=12, hid_dim=16)
    trainer = SGDTrainer(cost, Adam(learning_rate=1e-3), seed=0)
    feeder = data.DataFeeder({"words": "ids_seq", "label": "int"}, max_len=32)
    reader = data.batch(data.datasets.imdb("train", vocab_size=200, n=64), 16)
    costs = _first_last_costs(trainer, reader, feeder, steps=3)
    assert np.isfinite(costs).all()


def test_movielens_net_learns():
    cost, pred = models.movielens_net(100, 80, emb_dim=16, hid_dim=16)
    trainer = SGDTrainer(cost, Adam(learning_rate=1e-2), seed=0)
    feeder = data.DataFeeder({"user_id": "int", "movie_id": "int", "score": "dense"})

    def to_row(sample):
        u, m, r = sample
        return (u, m, [r])

    reader = data.batch(data.map_readers(to_row, data.datasets.movielens(
        "train", n_users=100, n_movies=80, n=512)), 64)
    costs = _first_last_costs(trainer, reader, feeder, steps=8)
    assert costs[-1] < costs[0]


class TestSeq2Seq:
    def _model_and_batch(self, rng, V=80, B=4, S=10, T=12):
        m = models.Seq2SeqAttention(src_vocab=V, trg_vocab=V, emb_dim=16,
                                    enc_dim=12, dec_dim=12, att_dim=10)
        params = m.init(jax.random.PRNGKey(0))
        src = rng.randint(3, V, (B, S)).astype(np.int32)
        src_len = np.array([10, 6, 3, 8], np.int32)
        trg_core = rng.randint(3, V, (B, T - 1)).astype(np.int32)
        trg_in = np.concatenate([np.zeros((B, 1), np.int32), trg_core], 1)
        trg_next = np.concatenate([trg_core, np.ones((B, 1), np.int32)], 1)
        trg_len = np.array([12, 7, 4, 9], np.int32)
        batch = {
            "src_ids": jnp.asarray(src), "src_len": jnp.asarray(src_len),
            "trg_in": jnp.asarray(trg_in), "trg_next": jnp.asarray(trg_next),
            "trg_len": jnp.asarray(trg_len),
        }
        return m, params, batch

    def test_loss_finite_and_grads_flow(self, rng):
        m, params, batch = self._model_and_batch(rng)
        loss, grads = jax.value_and_grad(m.loss)(params, batch)
        assert np.isfinite(float(loss))
        for k, g in grads.items():
            assert np.all(np.isfinite(np.asarray(g))), k
        # all major weights get gradient signal
        for k in ("src_emb", "trg_emb", "att_v", "dec_wh", "out_w", "boot_w"):
            assert float(jnp.sum(jnp.abs(grads[k]))) > 0, k

    def test_loss_padding_invariance(self, rng):
        m, params, batch = self._model_and_batch(rng)
        l1 = float(m.loss(params, batch))
        # extend source padding
        pad = jnp.asarray(rng.randint(3, 80, (4, 5)).astype(np.int32))
        batch2 = dict(batch)
        batch2["src_ids"] = jnp.concatenate([batch["src_ids"], pad], 1)
        l2 = float(m.loss(params, batch2))
        np.testing.assert_allclose(l1, l2, rtol=1e-5)

    def test_training_reduces_loss(self, rng):
        m, params, batch = self._model_and_batch(rng)
        from paddle_tpu.param.optimizers import Adam as AdamOpt

        opt = AdamOpt(learning_rate=5e-3)
        s = opt.init_state(params)

        @jax.jit
        def step(p, s):
            loss, g = jax.value_and_grad(m.loss)(p, batch)
            p2, s2 = opt.update(p, g, s)
            return loss, p2, s2

        losses = []
        for _ in range(30):
            loss, params, s = step(params, s)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8

    def test_beam_search_shapes_and_order(self, rng):
        m, params, batch = self._model_and_batch(rng)
        toks, scores = jax.jit(
            lambda p, s, l: m.beam_search(p, s, l, beam_size=3, max_len=8)
        )(params, batch["src_ids"], batch["src_len"])
        assert toks.shape == (4, 3, 8)
        assert scores.shape == (4, 3)
        sn = np.asarray(scores)
        assert np.all(sn[:, 0] >= sn[:, 1]) and np.all(sn[:, 1] >= sn[:, 2])
        assert np.asarray(toks).min() >= 0 and np.asarray(toks).max() < 80

    def test_greedy_equals_beam1_top(self, rng):
        m, params, batch = self._model_and_batch(rng)
        g_toks, _ = m.greedy_decode(params, batch["src_ids"], batch["src_len"], max_len=6)
        b_toks, _ = m.beam_search(params, batch["src_ids"], batch["src_len"],
                                  beam_size=1, max_len=6)
        np.testing.assert_array_equal(np.asarray(g_toks), np.asarray(b_toks[:, 0]))

    def test_beam_improves_score_over_greedy(self, rng):
        m, params, batch = self._model_and_batch(rng)
        _, s1 = m.beam_search(params, batch["src_ids"], batch["src_len"],
                              beam_size=1, max_len=8)
        _, s4 = m.beam_search(params, batch["src_ids"], batch["src_len"],
                              beam_size=4, max_len=8)
        assert np.all(np.asarray(s4[:, 0]) >= np.asarray(s1[:, 0]) - 1e-4)


class TestImageBenchNets:
    """AlexNet / GoogLeNet v1 — the reference's published image benchmark
    configs (benchmark/paddle/image/{alexnet,googlenet}.py)."""

    def test_alexnet_shapes_and_train_step(self, rng):
        import paddle_tpu.nn as nn
        from paddle_tpu.models import alexnet
        from paddle_tpu.param.optimizers import Momentum
        from paddle_tpu.trainer import SGDTrainer

        nn.reset_naming()
        cost, logits = alexnet(num_classes=10, height=67, width=67)  # small img
        tr = SGDTrainer(cost=cost, optimizer=Momentum(learning_rate=0.01),
                        seed=1)
        feed = {"pixel": np.random.RandomState(0).rand(2, 67, 67, 3).astype(np.float32),
                "label": np.zeros((2, 1), np.int64)}
        c0 = float(tr.train_batch(feed))
        c1 = float(tr.train_batch(feed))
        assert np.isfinite(c0) and np.isfinite(c1)

    def test_googlenet_inception_channels(self, rng):
        import jax

        import paddle_tpu.nn as nn
        from paddle_tpu.models import googlenet

        nn.reset_naming()
        cost, logits = googlenet(num_classes=10, height=224, width=224)
        # the stage table must land on a 7x7 map before the final avg pool,
        # then 1x1 (a degenerate 0x0 map silently made logits = bias once)
        pre_fc = logits.parents[0]
        assert pre_fc.meta.get("hw") == (1, 1), pre_fc.meta
        topo = nn.Topology([cost, logits])
        params, state = topo.init(jax.random.PRNGKey(0))
        rs = np.random.RandomState(0)
        feed = {"pixel": rs.rand(2, 224, 224, 3).astype(np.float32),
                "label": np.zeros((2, 1), np.int64)}
        outs, _ = topo.apply(params, state, feed, train=False)
        lg = np.asarray(outs[logits.name].value)
        assert lg.shape == (2, 10)
        assert np.isfinite(float(outs[cost.name].value))
        # logits must actually depend on the pixels
        feed2 = dict(feed, pixel=rs.rand(2, 224, 224, 3).astype(np.float32))
        outs2, _ = topo.apply(params, state, feed2, train=False)
        assert np.abs(lg - np.asarray(outs2[logits.name].value)).max() > 1e-6


def test_inception_fused_reduce_equivalence(rng):
    """fused_reduce merges the three input 1x1 convs into one — with the
    merged kernel/bias set to the concat of the three, the module must
    compute the IDENTICAL function (pins the slice-offset wiring)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.image_bench import _inception

    spec = (4, 3, 5, 2, 3, 3)  # f1, f3r, f3, f5r, f5, proj

    def build(fused):
        nn.reset_naming()
        x = nn.data("pixel", size=8, height=8, width=8)
        out = _inception(x, *spec, fused_reduce=fused)
        return nn.Topology(out), out.name

    topo_u, out_u = build(False)
    topo_f, out_f = build(True)
    params_u, state_u = topo_u.init(jax.random.PRNGKey(0))

    # creation order: unfused convs = b1, r3, b3, r5, b5, bp (checkpoint
    # name compatibility); fused convs = red(=concat of b1,r3,r5), b3, b5,
    # bp — so merged slots are unfused indices [0, 1, 3] and the tail maps
    # [b3, b5, bp] = unfused [2, 4, 5]
    def conv_params(params):
        ws = sorted(k for k in params if k.endswith(".w0"))
        bs = sorted(k for k in params if k.endswith(".wbias"))
        return ws, bs

    ws_u, bs_u = conv_params(params_u)
    params_f, state_f = topo_f.init(jax.random.PRNGKey(1))
    ws_f, bs_f = conv_params(params_f)
    assert len(ws_u) == 6 and len(ws_f) == 4
    merged_w = jnp.concatenate([params_u[ws_u[0]], params_u[ws_u[1]],
                                params_u[ws_u[3]]], axis=-1)
    assert params_f[ws_f[0]].shape == merged_w.shape
    params_f = dict(params_f)
    params_f[ws_f[0]] = merged_w
    params_f[bs_f[0]] = jnp.concatenate(
        [params_u[bs_u[0]], params_u[bs_u[1]], params_u[bs_u[3]]])
    for fu, un in zip(ws_f[1:], [ws_u[2], ws_u[4], ws_u[5]]):
        params_f[fu] = params_u[un]
    for fu, un in zip(bs_f[1:], [bs_u[2], bs_u[4], bs_u[5]]):
        params_f[fu] = params_u[un]

    feed = {"pixel": rng.randn(2, 8, 8, 8).astype(np.float32)}
    y_u, _ = topo_u.apply(params_u, state_u, feed, train=False)
    y_f, _ = topo_f.apply(params_f, state_f, feed, train=False)
    np.testing.assert_allclose(np.asarray(y_u[out_u].value),
                               np.asarray(y_f[out_f].value),
                               rtol=1e-5, atol=1e-6)
