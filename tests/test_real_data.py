"""Real-data ingestion tests (VERDICT r3 item 3).

Two tiers:

1. FORMAT tests — always run.  Each builds a miniature fixture in the REAL
   on-disk format (pickle tarball, aclImdb tar, ml-1m zip, PTB tgz, CoNLL
   words/props gz pair, ...) under a tmp $PADDLE_TPU_DATA_HOME and asserts
   the loader parses it exactly.  This proves the parse path without the
   multi-GB downloads (no egress here).
2. CONVERGENCE tests — gated on the actual datasets being present under
   $PADDLE_TPU_DATA_HOME (skip otherwise): mnist LeNet >=97% test accuracy,
   imdb stacked-LSTM >=85%, wmt14 seq2seq loss well below the uniform
   floor — the reference's train-on-real-data evidence
   (test_TrainerOnePass analog).
"""

import gzip
import io
import os
import pickle
import tarfile
import zipfile

import numpy as np
import pytest

import paddle_tpu.data.datasets as D


@pytest.fixture
def data_home(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_DATA_HOME", str(tmp_path))
    D._DICT_CACHE.clear()
    yield tmp_path
    D._DICT_CACHE.clear()


def _add_bytes(tf, name, payload):
    info = tarfile.TarInfo(name)
    info.size = len(payload)
    tf.addfile(info, io.BytesIO(payload))


# ---------------------------------------------------------------------------
# format tier
# ---------------------------------------------------------------------------


def test_cifar10_pickle_tarball(data_home):
    d = data_home / "cifar"
    d.mkdir()
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (4, 3072), np.uint8)  # CHW plane order rows
    with tarfile.open(d / "cifar-10-python.tar.gz", "w:gz") as tf:
        _add_bytes(tf, "cifar-10-batches-py/data_batch_1",
                   pickle.dumps({b"data": imgs[:2], b"labels": [3, 7]}, 2))
        _add_bytes(tf, "cifar-10-batches-py/test_batch",
                   pickle.dumps({b"data": imgs[2:], b"labels": [1, 9]}, 2))
    train = list(D.cifar10("train")())
    test = list(D.cifar10("test")())
    assert [l for _, l in train] == [3, 7] and [l for _, l in test] == [1, 9]
    img0, _ = train[0]
    assert img0.shape == (32, 32, 3) and img0.dtype == np.float32
    # CHW plane -> HWC pixel: red channel of pixel (0,0) is row byte 0
    np.testing.assert_allclose(img0[0, 0, 0], imgs[0, 0] / 255.0)
    np.testing.assert_allclose(img0[0, 0, 1], imgs[0, 1024] / 255.0)


def test_imdb_aclimdb_tarball(data_home):
    d = data_home / "imdb"
    d.mkdir()
    docs = {
        "aclImdb/train/pos/0_9.txt": b"Great movie, great acting!",
        "aclImdb/train/neg/0_2.txt": b"terrible terrible plot...",
        "aclImdb/test/pos/0_8.txt": b"great plot",
        "aclImdb/test/neg/0_3.txt": b"awful",
    }
    with tarfile.open(d / "aclImdb_v1.tar.gz", "w:gz") as tf:
        for name, payload in docs.items():
            _add_bytes(tf, name, payload)
    # dict from TRAIN only: great x3, terrible x2, rest x1 (punctuation
    # stripped, lowered); vocab cap 4 => great=0, terrible=1, acting=2, <unk>
    r = list(D.imdb("train", vocab_size=4)())
    assert len(r) == 2
    (pos_ids, pos_lab), (neg_ids, neg_lab) = sorted(r, key=lambda x: -x[1])
    assert pos_lab == 1 and neg_lab == 0
    # dict from train: great(2)=0, terrible(2)=1, acting(1)=2, <unk>=3
    assert pos_ids == [0, 3, 0, 2]           # great movie<unk> great acting
    assert neg_ids == [1, 1, 3]              # terrible terrible plot<unk>
    test_rows = list(D.imdb("test", vocab_size=4)())
    assert {lab for _, lab in test_rows} == {0, 1}


def test_wmt14_tgz(data_home):
    d = data_home / "wmt14"
    d.mkdir()
    src_dict = b"<s>\n<e>\n<unk>\nle\nchat\n"
    trg_dict = b"<s>\n<e>\n<unk>\nthe\ncat\n"
    train = b"le chat\tthe cat\nle " + b"x " * 90 + b"\tthe cat\n"
    test = b"chat\tcat\n"
    with tarfile.open(d / "wmt14.tgz", "w:gz") as tf:
        _add_bytes(tf, "wmt14/src.dict", src_dict)
        _add_bytes(tf, "wmt14/trg.dict", trg_dict)
        _add_bytes(tf, "wmt14/train/train", train)
        _add_bytes(tf, "wmt14/test/test", test)
    rows = list(D.wmt14("train", dict_size=5)())
    assert rows == [([0, 3, 4, 1], [0, 3, 4], [3, 4, 1])]  # >80-token dropped
    rows = list(D.wmt14("test", dict_size=5)())
    assert rows == [([0, 4, 1], [0, 4], [4, 1])]
    # unknown words map to UNK_IDX=2
    with tarfile.open(d / "wmt14.tgz", "w:gz") as tf:
        _add_bytes(tf, "wmt14/src.dict", src_dict)
        _add_bytes(tf, "wmt14/trg.dict", trg_dict)
        _add_bytes(tf, "wmt14/train/train", b"mystery chat\tthe dog\n")
    assert list(D.wmt14("train", dict_size=5)()) == [
        ([0, 2, 4, 1], [0, 3, 2], [3, 2, 1])]


def test_movielens_ml1m_zip(data_home):
    d = data_home / "movielens"
    d.mkdir()
    with zipfile.ZipFile(d / "ml-1m.zip", "w") as z:
        z.writestr("ml-1m/users.dat",
                   "1::F::1::10::48067\n2::M::56::16::70072\n")
        z.writestr("ml-1m/movies.dat",
                   "1::Toy Story (1995)::Animation|Comedy\n"
                   "2::Jumanji (1995)::Adventure\n")
        # many lines so both splits are non-empty under the random split
        ratings = "".join(f"{1 + i % 2}::{1 + i % 2}::{1 + i % 5}::0\n"
                          for i in range(200))
        z.writestr("ml-1m/ratings.dat", ratings)
    plain = list(D.movielens("train")())
    plain_test = list(D.movielens("test")())
    assert 0 < len(plain_test) < len(plain)  # ~10% test split
    u, m, r = plain[0]
    assert u in (0, 1) and m in (0, 1) and 1.0 <= r <= 5.0  # 0-based ids
    feats = list(D.movielens_features("train")())
    uid, g, age, job, mid, cats, title, score = feats[0]
    # user 1 is F (gender 1), age bucket index of 1 -> 0, job 10
    row_u1 = [f for f in feats if f[0] == 0][0]
    assert row_u1[1] == 1 and row_u1[2] == 0 and row_u1[3] == 10
    # categories sorted alphabetically: Adventure=0, Animation=1, Comedy=2
    row_m1 = [f for f in feats if f[4] == 0][0]
    assert row_m1[5] == [1, 2]
    assert len(row_m1[6]) == 2  # 'toy story' title words
    assert 1.0 <= row_m1[7][0] <= 5.0


def test_uci_housing_table(data_home):
    d = data_home / "uci_housing"
    d.mkdir()
    rng = np.random.RandomState(1)
    rows = rng.rand(10, 14) * 10
    with open(d / "housing.data", "w") as f:
        for row in rows:
            f.write(" ".join(f"{v:.4f}" for v in row) + "\n")
    train = list(D.uci_housing("train")())
    test = list(D.uci_housing("test")())
    assert len(train) == 8 and len(test) == 2  # 80/20 head/tail
    x, y = train[0]
    assert x.shape == (13,) and x.dtype == np.float32
    # normalization: (x - mean) / (max - min) per feature, price untouched
    col0 = np.round(rows[:, 0], 4)  # the file stores 4 decimals
    expect = (col0[0] - col0.mean()) / (col0.max() - col0.min())
    np.testing.assert_allclose(x[0], expect, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(y, rows[0, 13], rtol=1e-4)


def test_imikolov_ptb_tgz(data_home):
    d = data_home / "imikolov"
    d.mkdir()
    with tarfile.open(d / "simple-examples.tgz", "w:gz") as tf:
        _add_bytes(tf, "./simple-examples/data/ptb.train.txt",
                   b"a b a\na b c <unk>\n")
        _add_bytes(tf, "./simple-examples/data/ptb.valid.txt", b"b a\n")
    # freqs over train+valid incl per-line <s>/<e>: a=4 b=4 <s>=3 <e>=3 c=1;
    # corpus '<unk>' excluded; tie order alphabetical-ish by (-freq, word)
    rows = list(D.imikolov("train", vocab_size=6, ngram=3)())
    wd = D.formats.imikolov_word_dict(str(d / "simple-examples.tgz"), 6)
    assert wd["<unk>"] == 5 and len(wd) == 6
    s, e, a, b = wd["<s>"], wd["<e>"], wd["a"], wd["b"]
    # line 1: <s> a b a <e> -> 3 trigrams
    assert rows[0] == (s, a, b) and rows[1] == (a, b, a) and rows[2] == (b, a, e)
    # line 2 contains the corpus literal '<unk>' -> maps to the unk id
    assert any(wd["<unk>"] in r for r in rows[3:])
    valid = list(D.imikolov("test", vocab_size=6, ngram=3)())
    assert valid[0] == (s, b, a)


def test_conll05_tarball(data_home):
    d = data_home / "conll05st"
    d.mkdir()
    words = b"The\ncat\nsat\n\n"
    props = b"-\t(A0*\n-\t*)\nsit\t(V*)\n\n"

    def gz(payload):
        buf = io.BytesIO()
        with gzip.GzipFile(fileobj=buf, mode="wb") as g:
            g.write(payload)
        return buf.getvalue()

    with tarfile.open(d / "conll05st-tests.tar.gz", "w:gz") as tf:
        _add_bytes(tf, "conll05st-release/test.wsj/words/test.wsj.words.gz",
                   gz(words))
        _add_bytes(tf, "conll05st-release/test.wsj/props/test.wsj.props.gz",
                   gz(props))
    (d / "wordDict.txt").write_text("The\ncat\nsat\n")
    (d / "verbDict.txt").write_text("-\nsit\n")
    (d / "targetDict.txt").write_text("O\nB-A0\nI-A0\nB-V\n")
    rows = list(D.conll05("train")())
    assert rows == [([0, 1, 2], 1, [1, 2, 3])]  # words, verb 'sit', BIO ids
    frows = list(D.conll05_features("train")())
    w, c2, c1, c0, p1, p2, verb, mark, lab = frows[0]
    assert w == [0, 1, 2] and lab == [1, 2, 3]
    assert c0 == [2, 2, 2]          # predicate word 'sat' broadcast
    assert c1 == [1, 1, 1]          # ctx-1 'cat'
    assert c2 == [0, 0, 0]          # ctx-2 'The'
    assert mark == [1, 1, 1]        # 5-window clipped to the 3-token sentence
    assert verb == [1, 1, 1]


def test_sentiment_movie_reviews_dir(data_home):
    d = data_home / "sentiment" / "movie_reviews"
    for sense, texts in (("pos", ["good good fun", "good story"]),
                         ("neg", ["bad bad boring", "bad end"])):
        (d / sense).mkdir(parents=True)
        for i, t in enumerate(texts):
            (d / sense / f"cv{i}.txt").write_text(t)
    train = list(D.sentiment("train", vocab_size=4)())
    test = list(D.sentiment("test", vocab_size=4)())
    # 4 files interleaved neg,pos,neg,pos; head 80% (3 files) = train
    assert len(train) == 3 and len(test) == 1
    assert [lab for _, lab in train] == [0, 1, 0]
    wd = D.formats.movie_reviews_word_dict(str(d), 4)
    assert wd["bad"] == 0 and wd["good"] == 1 and len(wd) == 4
    for ids, _ in train + test:
        assert all(0 <= i < 4 for i in ids)


def test_mnist_idx_files(data_home):
    import struct
    d = data_home / "mnist"
    d.mkdir()
    rng = np.random.RandomState(2)
    imgs = rng.randint(0, 256, (3, 28, 28), np.uint8)
    labs = np.array([4, 0, 9], np.uint8)
    with open(d / "train-images-idx3-ubyte", "wb") as f:
        f.write(struct.pack(">IIII", 2051, 3, 28, 28))
        f.write(imgs.tobytes())
    with open(d / "train-labels-idx1-ubyte", "wb") as f:
        f.write(struct.pack(">II", 2049, 3))
        f.write(labs.tobytes())
    rows = list(D.mnist("train")())
    assert [l for _, l in rows] == [4, 0, 9]
    np.testing.assert_allclose(rows[0][0][:, :, 0], imgs[0] / 255.0)


def test_synthetic_fallback_when_absent(data_home):
    # empty DATA_HOME: every loader must fall back to its synthetic stream
    for maker in (D.mnist, D.cifar10, D.imdb, D.wmt14, D.movielens,
                  D.movielens_features, D.uci_housing, D.imikolov,
                  D.conll05, D.conll05_features, D.sentiment):
        rows = list(__import__("itertools").islice(maker("train")(), 3))
        assert len(rows) == 3, maker.__name__


# ---------------------------------------------------------------------------
# convergence tier (gated on real datasets being present)
# ---------------------------------------------------------------------------


def _have(*parts):
    return os.path.exists(os.path.join(D.data_home(), *parts))


@pytest.mark.skipif(not _have("mnist", "train-images-idx3-ubyte"),
                    reason="real MNIST not under $PADDLE_TPU_DATA_HOME")
def test_real_mnist_lenet_converges():
    """LeNet-5 to >=97% test accuracy on real MNIST (one pass) — the
    test_TrainerOnePass analog on actual data."""
    import jax.numpy as jnp

    import paddle_tpu.nn as nn
    from paddle_tpu.models import lenet5
    from paddle_tpu.param.optimizers import Adam
    from paddle_tpu.trainer import SGDTrainer

    nn.reset_naming()
    cost, logits = lenet5()
    trainer = SGDTrainer(cost, Adam(learning_rate=1e-3), seed=0)
    B = 128

    def batches(split):
        xs, ys = [], []
        for img, lab in D.mnist(split)():
            xs.append(img)
            ys.append(lab)
            if len(xs) == B:
                yield {"pixel": np.stack(xs),
                       "label": np.asarray(ys, np.int32)[:, None]}
                xs, ys = [], []

    for epoch in range(2):
        for feed in batches("train"):
            trainer.train_batch(feed)
    correct = total = 0
    for feed in batches("t10k"):
        outs = trainer.infer(logits, feed)
        pred = np.argmax(np.asarray(outs["logits"]), -1)
        correct += int((pred == feed["label"][:, 0]).sum())
        total += len(pred)
    acc = correct / total
    assert acc >= 0.97, f"LeNet test accuracy {acc:.4f} < 0.97"


@pytest.mark.skipif(not _have("imdb", "aclImdb_v1.tar.gz"),
                    reason="real IMDB not under $PADDLE_TPU_DATA_HOME")
def test_real_imdb_stacked_lstm_converges():
    """Stacked-LSTM sentiment to >=85% test accuracy on real IMDB."""
    import paddle_tpu.nn as nn
    from paddle_tpu.models import stacked_lstm_net
    from paddle_tpu.param.optimizers import Adam
    from paddle_tpu.trainer import SGDTrainer

    V, B, T = 5000, 64, 200
    nn.reset_naming()
    cost, logits = stacked_lstm_net(V, hid_dim=128, stacked_num=3)
    trainer = SGDTrainer(cost, Adam(learning_rate=2e-3), seed=0)

    def batches(split):
        xs, ls, ys = [], [], []
        for ids, lab in D.imdb(split, vocab_size=V)():
            ids = ids[:T]
            xs.append(np.pad(ids, (0, T - len(ids))).astype(np.int32))
            ls.append(len(ids))
            ys.append(lab)
            if len(xs) == B:
                yield {"words": (np.stack(xs), np.asarray(ls, np.int32)),
                       "label": np.asarray(ys, np.int32)[:, None]}
                xs, ls, ys = [], [], []

    for feed in batches("train"):
        trainer.train_batch(feed)
    correct = total = 0
    for feed in batches("test"):
        outs = trainer.infer(logits, feed)
        pred = np.argmax(np.asarray(outs["logits"]), -1)
        correct += int((pred == feed["label"][:, 0]).sum())
        total += len(pred)
    acc = correct / total
    assert acc >= 0.85, f"IMDB test accuracy {acc:.4f} < 0.85"


@pytest.mark.skipif(not _have("wmt14", "wmt14.tgz"),
                    reason="real WMT14 not under $PADDLE_TPU_DATA_HOME")
def test_real_wmt14_seq2seq_loss_decreases():
    """Flagship seq2seq on real WMT14 pairs: teacher-forced loss must drop
    well below the uniform-vocabulary floor within a few hundred batches
    (the demo/seqToseq smoke on actual data)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import Seq2SeqAttention
    from paddle_tpu.param.optimizers import Adam

    V, B, S, T = 30000, 64, 32, 32
    m = Seq2SeqAttention(src_vocab=V, trg_vocab=V)
    params = m.init(jax.random.PRNGKey(0))
    opt = Adam(learning_rate=5e-4)
    state = opt.init_state(params)

    @jax.jit
    def step(p, s, batch):
        l, g = jax.value_and_grad(m.loss)(p, batch)
        p2, s2 = opt.update(p, g, s)
        return l, p2, s2

    def batches():
        rows = []
        for src, trg_in, trg_next in D.wmt14("train")():
            if len(src) > S or len(trg_in) > T:
                continue
            rows.append((src, trg_in, trg_next))
            if len(rows) == B:
                def pad(seqs, L):
                    out = np.zeros((B, L), np.int32)
                    for i, q in enumerate(seqs):
                        out[i, :len(q)] = q
                    return out
                yield {
                    "src_ids": pad([r[0] for r in rows], S),
                    "src_len": np.array([len(r[0]) for r in rows], np.int32),
                    "trg_in": pad([r[1] for r in rows], T),
                    "trg_next": pad([r[2] for r in rows], T),
                    "trg_len": np.array([len(r[1]) for r in rows], np.int32),
                }
                rows = []

    losses = []
    for i, feed in enumerate(batches()):
        l, params, state = step(params, state, feed)
        losses.append(float(l))
        if i >= 300:
            break
    assert np.isfinite(losses[-1])
    # uniform guess over 30k vocab is ln(30000) ~ 10.3; real structure must
    # pull the model clearly below it
    assert np.mean(losses[-20:]) < 7.0, np.mean(losses[-20:])
