"""Vocab-tiled fused readout+CE Pallas kernels (interpret mode on CPU) vs
the XLA path of ops/losses.sequence_softmax_ce_readout — loss and all three
gradients, including a vocab that does NOT divide the tile (padding with
-1e30 bias must keep statistics and gradients exact) and masked rows."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops import losses as L
from paddle_tpu.ops.pallas_kernels import pallas_available

pytestmark = pytest.mark.skipif(not pallas_available(),
                                reason="pallas unavailable")


def _data(rng, B=4, T=6, D=128, V=300, lens=(6, 4, 5, 2)):
    states = jnp.asarray(rng.randn(B, T, D).astype(np.float32) * 0.3)
    w = jnp.asarray(rng.randn(D, V).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(V).astype(np.float32) * 0.1)
    labels = jnp.asarray(rng.randint(0, V, (B, T)).astype(np.int32))
    mask = jnp.asarray((np.arange(T)[None]
                        < np.asarray(lens)[:, None]).astype(np.float32))
    return states, w, b, labels, mask


@pytest.mark.parametrize("V", [300, 256])  # non-divisible and exact tiles
def test_tiled_ce_matches_xla_path(monkeypatch, rng, V):
    states, w, b, labels, mask = _data(rng, V=V)

    def loss(states, w, b):
        return L.sequence_softmax_ce_readout(states, w, b, labels, mask)

    l_ref, g_ref = jax.value_and_grad(loss, argnums=(0, 1, 2))(states, w, b)
    monkeypatch.setattr(L, "_tiled_ce_cfg", lambda B, T, D, V: (8, 128))
    l_t, g_t = jax.value_and_grad(loss, argnums=(0, 1, 2))(states, w, b)
    np.testing.assert_allclose(float(l_ref), float(l_t), rtol=1e-6)
    for a, c, nm in zip(g_ref, g_t, ("d_states", "d_w", "d_b")):
        a = np.asarray(a, np.float64)
        c = np.asarray(c, np.float64)
        scale = np.abs(a).max() + 1e-12
        np.testing.assert_allclose(a / scale, c / scale, atol=2e-6,
                                   err_msg=nm)


def test_tiled_ce_bf16_operands(monkeypatch, rng):
    """bf16 compute policy (the production path): tiled vs XLA stay within
    bf16 rounding of each other."""
    monkeypatch.setenv("PADDLE_TPU_COMPUTE_DTYPE", "bfloat16")
    from paddle_tpu.utils.flags import FLAGS

    monkeypatch.setattr(FLAGS, "compute_dtype", "bfloat16")
    states, w, b, labels, mask = _data(rng)

    def loss(states, w, b):
        return L.sequence_softmax_ce_readout(states, w, b, labels, mask)

    l_ref, g_ref = jax.value_and_grad(loss, argnums=(0, 1, 2))(states, w, b)
    monkeypatch.setattr(L, "_tiled_ce_cfg", lambda B, T, D, V: (8, 128))
    l_t, g_t = jax.value_and_grad(loss, argnums=(0, 1, 2))(states, w, b)
    assert abs(float(l_ref) - float(l_t)) / abs(float(l_ref)) < 2e-2
    for a, c, nm in zip(g_ref, g_t, ("d_states", "d_w", "d_b")):
        a = np.asarray(a, np.float64)
        c = np.asarray(c, np.float64)
        scale = np.abs(a).max() + 1e-12
        np.testing.assert_allclose(a / scale, c / scale, atol=3e-2,
                                   err_msg=nm)


def test_gate_rejects_cpu_and_bad_shapes():
    import jax as _jax

    if _jax.default_backend() not in ("tpu", "axon"):
        assert L._tiled_ce_cfg(4, 8, 128, 300) is None  # CPU backend
    # lane-misaligned D can never tile
    from paddle_tpu.utils.flags import FLAGS

    old = FLAGS.use_pallas_ce
    try:
        FLAGS.use_pallas_ce = True
        assert L._tiled_ce_cfg(4, 8, 100, 300) is None or \
            _jax.default_backend() not in ("tpu", "axon")
    finally:
        FLAGS.use_pallas_ce = old


def test_lse_readout_falls_back_below_sublane(monkeypatch, rng):
    """ADVICE r5 / ops/losses.py:140 regression: when gcd(B*T, 64) < 8 the
    row tile would drop below the (8, 128) sublane — the recorded-A/B lse
    kernel must NOT be called (the XLA reduction takes over) and the
    numerics must match the default XLA path exactly.  B*T odd forces
    gcd == 1."""
    from paddle_tpu.ops import pallas_kernels as pk

    def boom(*a, **k):
        raise AssertionError("pallas lse called with a sub-sublane tile")

    monkeypatch.setattr(pk, "logsumexp_rows_pallas", boom)
    B, T, D, Vv = 3, 3, 16, 50  # B*T = 9 (odd): gcd(9, 64) == 1
    states = jnp.asarray(rng.randn(B, T, D).astype(np.float32) * 0.3)
    w = jnp.asarray(rng.randn(D, Vv).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(Vv).astype(np.float32) * 0.1)
    labels = jnp.asarray(rng.randint(0, Vv, (B, T)).astype(np.int32))
    mask = jnp.asarray((np.arange(T)[None] < np.array([3, 1, 2])[:, None])
                       .astype(np.float32))

    def fused(states, w, b):
        return L._ce_readout_fused(states, w, b, labels, mask)

    def ref(states, w, b):  # the default XLA branch
        return L.sequence_softmax_ce_readout(states, w, b, labels, mask)

    monkeypatch.setattr(L, "_tiled_ce_cfg", lambda *a: None)
    l_f, g_f = jax.value_and_grad(fused, argnums=(0, 1, 2))(states, w, b)
    l_r, g_r = jax.value_and_grad(ref, argnums=(0, 1, 2))(states, w, b)
    np.testing.assert_allclose(float(l_f), float(l_r), rtol=1e-6)
    for a, c, nm in zip(g_r, g_f, ("d_states", "d_w", "d_b")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-5, atol=1e-6, err_msg=nm)


def test_lse_readout_uses_kernel_when_sublane_aligned(monkeypatch, rng):
    from paddle_tpu.ops import pallas_kernels as pk

    calls = []
    orig = pk.logsumexp_rows_pallas

    def spy(*a, **k):
        calls.append(k.get("row_tile"))
        return orig(*a, **k)

    monkeypatch.setattr(pk, "logsumexp_rows_pallas", spy)
    B, T, D, Vv = 2, 4, 16, 50  # B*T = 8: gcd(8, 64) == 8, kernel stays
    states = jnp.asarray(rng.randn(B, T, D).astype(np.float32) * 0.3)
    w = jnp.asarray(rng.randn(D, Vv).astype(np.float32) * 0.1)
    b = jnp.zeros((Vv,), jnp.float32)
    labels = jnp.asarray(rng.randint(0, Vv, (B, T)).astype(np.int32))
    mask = jnp.ones((B, T), jnp.float32)
    loss = L._ce_readout_fused(states, w, b, labels, mask)
    assert calls == [8]
    assert np.isfinite(float(loss))
