"""Vocab-tiled fused readout+CE Pallas kernels (interpret mode on CPU) vs
the XLA path of ops/losses.sequence_softmax_ce_readout — loss and all three
gradients, including a vocab that does NOT divide the tile (padding with
-1e30 bias must keep statistics and gradients exact) and masked rows."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops import losses as L
from paddle_tpu.ops.pallas_kernels import pallas_available

pytestmark = pytest.mark.skipif(not pallas_available(),
                                reason="pallas unavailable")


def _data(rng, B=4, T=6, D=128, V=300, lens=(6, 4, 5, 2)):
    states = jnp.asarray(rng.randn(B, T, D).astype(np.float32) * 0.3)
    w = jnp.asarray(rng.randn(D, V).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(V).astype(np.float32) * 0.1)
    labels = jnp.asarray(rng.randint(0, V, (B, T)).astype(np.int32))
    mask = jnp.asarray((np.arange(T)[None]
                        < np.asarray(lens)[:, None]).astype(np.float32))
    return states, w, b, labels, mask


@pytest.mark.parametrize("V", [300, 256])  # non-divisible and exact tiles
def test_tiled_ce_matches_xla_path(monkeypatch, rng, V):
    states, w, b, labels, mask = _data(rng, V=V)

    def loss(states, w, b):
        return L.sequence_softmax_ce_readout(states, w, b, labels, mask)

    l_ref, g_ref = jax.value_and_grad(loss, argnums=(0, 1, 2))(states, w, b)
    monkeypatch.setattr(L, "_tiled_ce_cfg", lambda B, T, D, V: (8, 128))
    l_t, g_t = jax.value_and_grad(loss, argnums=(0, 1, 2))(states, w, b)
    np.testing.assert_allclose(float(l_ref), float(l_t), rtol=1e-6)
    for a, c, nm in zip(g_ref, g_t, ("d_states", "d_w", "d_b")):
        a = np.asarray(a, np.float64)
        c = np.asarray(c, np.float64)
        scale = np.abs(a).max() + 1e-12
        np.testing.assert_allclose(a / scale, c / scale, atol=2e-6,
                                   err_msg=nm)


def test_tiled_ce_bf16_operands(monkeypatch, rng):
    """bf16 compute policy (the production path): tiled vs XLA stay within
    bf16 rounding of each other."""
    monkeypatch.setenv("PADDLE_TPU_COMPUTE_DTYPE", "bfloat16")
    from paddle_tpu.utils.flags import FLAGS

    monkeypatch.setattr(FLAGS, "compute_dtype", "bfloat16")
    states, w, b, labels, mask = _data(rng)

    def loss(states, w, b):
        return L.sequence_softmax_ce_readout(states, w, b, labels, mask)

    l_ref, g_ref = jax.value_and_grad(loss, argnums=(0, 1, 2))(states, w, b)
    monkeypatch.setattr(L, "_tiled_ce_cfg", lambda B, T, D, V: (8, 128))
    l_t, g_t = jax.value_and_grad(loss, argnums=(0, 1, 2))(states, w, b)
    assert abs(float(l_ref) - float(l_t)) / abs(float(l_ref)) < 2e-2
    for a, c, nm in zip(g_ref, g_t, ("d_states", "d_w", "d_b")):
        a = np.asarray(a, np.float64)
        c = np.asarray(c, np.float64)
        scale = np.abs(a).max() + 1e-12
        np.testing.assert_allclose(a / scale, c / scale, atol=3e-2,
                                   err_msg=nm)


def test_gate_rejects_cpu_and_bad_shapes():
    import jax as _jax

    if _jax.default_backend() not in ("tpu", "axon"):
        assert L._tiled_ce_cfg(4, 8, 128, 300) is None  # CPU backend
    # lane-misaligned D can never tile
    from paddle_tpu.utils.flags import FLAGS

    old = FLAGS.use_pallas_ce
    try:
        FLAGS.use_pallas_ce = True
        assert L._tiled_ce_cfg(4, 8, 100, 300) is None or \
            _jax.default_backend() not in ("tpu", "axon")
    finally:
        FLAGS.use_pallas_ce = old
