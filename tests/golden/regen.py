"""Regenerate golden protostr files. Run deliberately, review the git diff:

    JAX_PLATFORMS=cpu PADDLE_TPU_COMPUTE_DTYPE=float32 python tests/golden/regen.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from test_config import _simple_net  # noqa: E402

from paddle_tpu.config import dump_model_config, protostr  # noqa: E402

mc = dump_model_config(_simple_net(), "simple_net")
mc.framework_version = ""
mc.dtype_policy = ""
out = os.path.join(os.path.dirname(__file__), "simple_net.protostr")
with open(out, "w") as f:
    f.write(protostr(mc))
print("wrote", out)
