"""Regenerate golden protostr files. Run deliberately, review the git diff:

    JAX_PLATFORMS=cpu PADDLE_TPU_COMPUTE_DTYPE=float32 python tests/golden/regen.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import paddle_tpu.nn as nn  # noqa: E402
from paddle_tpu.config import dump_model_config, protostr  # noqa: E402

from golden_nets import GOLDEN_NETS  # noqa: E402
from test_config import _simple_net  # noqa: E402

HERE = os.path.dirname(__file__)


def write(name, topo):
    mc = dump_model_config(topo, name)
    mc.framework_version = ""
    mc.dtype_policy = ""
    out = os.path.join(HERE, f"{name}.protostr")
    with open(out, "w") as f:
        f.write(protostr(mc))
    print("wrote", out)


write("simple_net", _simple_net())
for name, builder in sorted(GOLDEN_NETS.items()):
    nn.reset_naming()
    topo, _ = builder()
    write(name, topo)
