// Python-free inference host over the XLA CPU PJRT client.
//
// The reference deploys with a pure-C process over its C++ engine
// (paddle/capi/gradient_machine.h:27-59).  The TPU-native analog: training
// exports the jitted inference function (weights embedded as constants) as
// an HloModuleProto bundle (paddle_tpu/config/deploy.py:export_aot_hlo),
// and THIS host — no Python, no jax, no paddle_tpu — compiles and runs it
// through the PJRT CPU client that ships inside libtensorflow_cc.
//
// Bundle layout (a directory):
//   model.hlo.pb   serialized xla.HloModuleProto (flat signature)
//   io.txt         one line per input:  in <f32|i32> <d0>x<d1>x...
//                  (outputs need no declaration; the host emits whatever
//                   the executable returns)
//   in<i>.bin      raw little-endian input buffers, row-major
// The host writes out<i>.bin next to them and prints one line per output:
//   out<i> <dtype> <dims> <bytes>
//
// Build (the only dependency is the tensorflow wheel's bundled XLA;
// paddle_tpu.config.deploy.build_aot_host runs exactly this):
//   g++ -O2 -std=c++17 -DNDEBUG -D_GLIBCXX_USE_CXX11_ABI=1 \
//       csrc/aot_host.cc -Icsrc/shim -I$TF/include \
//       -I$TF/include/external/highwayhash \
//       -I$TF/include/external/farmhash_archive/src \
//       -L$TF -l:libtensorflow_cc.so.2 -l:libtensorflow_framework.so.2 \
//       -Wl,-rpath,$TF -o aot_host
// -DNDEBUG is LOAD-BEARING: the wheel's absl is a release build, and
// absl's SwissTable layout differs between debug and NDEBUG — mixing our
// inlined header code with the library's (an ODR violation) corrupts
// every hash table and crashes at the first insert.

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

#include "xla/hlo/builder/xla_computation.h"
#include "xla/pjrt/pjrt_client.h"
#include "xla/pjrt/pjrt_executable.h"
#include "xla/pjrt/plugin/xla_cpu/cpu_client_options.h"
#include "xla/pjrt/plugin/xla_cpu/xla_cpu_pjrt_client.h"
#include "xla/primitive_util.h"
#include "xla/service/hlo.pb.h"
#include "xla/xla_data.pb.h"

namespace {

std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    fprintf(stderr, "cannot open %s\n", path.c_str());
    exit(2);
  }
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

struct InputSpec {
  xla::PrimitiveType type;
  std::vector<int64_t> dims;
};

xla::PrimitiveType ParseDtype(const std::string& s) {
  if (s == "f32") return xla::F32;
  if (s == "i32") return xla::S32;
  if (s == "f64") return xla::F64;
  if (s == "i64") return xla::S64;
  fprintf(stderr, "unsupported dtype %s\n", s.c_str());
  exit(2);
}

const char* DtypeName(xla::PrimitiveType t) {
  switch (t) {
    case xla::F32: return "f32";
    case xla::S32: return "i32";
    case xla::F64: return "f64";
    case xla::S64: return "i64";
    case xla::PRED: return "pred";
    default: return "other";
  }
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: %s <bundle_dir>\n", argv[0]);
    return 2;
  }
  const std::string dir = argv[1];

  // ---- parse io.txt ------------------------------------------------------
  std::vector<InputSpec> inputs;
  {
    std::ifstream io(dir + "/io.txt");
    if (!io) {
      fprintf(stderr, "cannot open %s/io.txt\n", dir.c_str());
      return 2;
    }
    std::string kind, dtype, dims;
    while (io >> kind >> dtype >> dims) {
      if (kind != "in") continue;
      InputSpec spec;
      spec.type = ParseDtype(dtype);
      if (dims != "scalar") {
        std::stringstream ds(dims);
        std::string d;
        while (std::getline(ds, d, 'x')) spec.dims.push_back(std::stoll(d));
      }
      inputs.push_back(std::move(spec));
    }
  }

  // ---- deserialize the module and build the executable -------------------
  xla::HloModuleProto proto;
  if (!proto.ParseFromString(ReadFile(dir + "/model.hlo.pb"))) {
    fprintf(stderr, "model.hlo.pb does not parse as HloModuleProto\n");
    return 2;
  }
  xla::XlaComputation computation(proto);

  xla::CpuClientOptions copts;
  copts.cpu_device_count = 1;
  // inline dispatch: a single-shot host has nothing to overlap, and it
  // keeps execution on the calling thread
  copts.asynchronous = false;
  auto client_or = xla::GetXlaPjrtCpuClient(std::move(copts));
  if (!client_or.ok()) {
    fprintf(stderr, "GetXlaPjrtCpuClient: %s\n",
            client_or.status().ToString().c_str());
    return 3;
  }
  std::unique_ptr<xla::PjRtClient> client = std::move(client_or).value();

  xla::CompileOptions compile_opts;
  auto exec_or = client->CompileAndLoad(computation, compile_opts);
  if (!exec_or.ok()) {
    fprintf(stderr, "CompileAndLoad: %s\n",
            exec_or.status().ToString().c_str());
    return 3;
  }
  auto executable = std::move(exec_or).value();

  // ---- inputs -> device buffers ------------------------------------------
  xla::PjRtDevice* device = client->addressable_devices()[0];
  auto mem_or = device->default_memory_space();
  if (!mem_or.ok()) {
    fprintf(stderr, "default_memory_space: %s\n",
            mem_or.status().ToString().c_str());
    return 3;
  }
  std::vector<std::string> raw(inputs.size());
  std::vector<std::unique_ptr<xla::PjRtBuffer>> buffers;
  for (size_t i = 0; i < inputs.size(); ++i) {
    raw[i] = ReadFile(dir + "/in" + std::to_string(i) + ".bin");
    size_t want = xla::primitive_util::ByteWidth(inputs[i].type);
    for (int64_t d : inputs[i].dims) want *= static_cast<size_t>(d);
    if (raw[i].size() != want) {
      fprintf(stderr,
              "in%zu.bin holds %zu bytes but io.txt declares %zu — wrong "
              "dtype, shape, or a truncated file\n",
              i, raw[i].size(), want);
      return 2;
    }
    auto buf_or = client->BufferFromHostBuffer(
        raw[i].data(), inputs[i].type, inputs[i].dims,
        /*byte_strides=*/std::nullopt,
        xla::PjRtClient::HostBufferSemantics::kImmutableUntilTransferCompletes,
        /*on_done_with_host_buffer=*/nullptr, mem_or.value(),
        /*device_layout=*/nullptr);
    if (!buf_or.ok()) {
      fprintf(stderr, "BufferFromHostBuffer(%zu): %s\n", i,
              buf_or.status().ToString().c_str());
      return 3;
    }
    buffers.push_back(std::move(buf_or).value());
  }

  // ---- execute ------------------------------------------------------------
  std::vector<xla::PjRtBuffer*> arg_ptrs;
  for (auto& b : buffers) arg_ptrs.push_back(b.get());
  xla::ExecuteOptions eopts;
  auto results_or = executable->Execute({arg_ptrs}, eopts);
  if (!results_or.ok()) {
    fprintf(stderr, "Execute: %s\n", results_or.status().ToString().c_str());
    return 3;
  }
  auto& results = results_or.value()[0];

  // ---- outputs -> raw files ----------------------------------------------
  // Read back through AcquireExternalReference + memcpy: on the CPU client
  // "device" memory IS host memory, so this is a zero-copy view — no
  // Literal allocation/relayout needed for row-major outputs.
  for (size_t i = 0; i < results.size(); ++i) {
    xla::PjRtBuffer* buf = results[i].get();
    auto size_or = buf->GetOnDeviceSizeInBytes();
    auto ref_or = buf->AcquireExternalReference();
    if (!size_or.ok() || !ref_or.ok()) {
      fprintf(stderr, "output %zu readback: %s %s\n", i,
              size_or.status().ToString().c_str(),
              ref_or.status().ToString().c_str());
      return 3;
    }
    const size_t nbytes = static_cast<size_t>(size_or.value());
    const void* p = ref_or.value()->OpaqueDeviceMemoryDataPointer();
    std::ofstream out(dir + "/out" + std::to_string(i) + ".bin",
                      std::ios::binary);
    out.write(reinterpret_cast<const char*>(p),
              static_cast<std::streamsize>(nbytes));
    out.close();
    std::string dims;
    for (int64_t d : buf->dimensions()) {
      if (!dims.empty()) dims += "x";
      dims += std::to_string(d);
    }
    if (dims.empty()) dims = "scalar";
    printf("out%zu %s %s %zu\n", i, DtypeName(buf->element_type()),
           dims.c_str(), nbytes);
  }
  return 0;
}
