/* Smoke driver for the C inference API (csrc/capi.cc) — loads a bundle,
 * feeds a float32 input named "x" of shape [2, dim], prints the "o" output.
 * Usage: capi_smoke <bundle.ptz> <dim>
 * The reference's analog is paddle/capi/examples. */

#include <stdio.h>
#include <stdlib.h>

extern int paddle_tpu_init(void);
extern const char* paddle_tpu_last_error(void);
extern void* paddle_tpu_model_load(const char* path);
extern void paddle_tpu_model_destroy(void* h);
extern char* paddle_tpu_model_info(void* h);
extern int paddle_tpu_feed(void* h, const char* name, const char* dtype,
                           const void* data, const long long* shape, int ndim,
                           const int* lengths, int n_lengths);
extern int paddle_tpu_forward(void* h, const char* output_name);
extern int paddle_tpu_output(void* h, const char* name, const float** data,
                             const long long** shape, int* ndim);

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s bundle.ptz dim\n", argv[0]);
    return 2;
  }
  int dim = atoi(argv[2]);
  if (paddle_tpu_init() != 0) {
    fprintf(stderr, "init failed: %s\n", paddle_tpu_last_error());
    return 1;
  }
  void* m = paddle_tpu_model_load(argv[1]);
  if (!m) {
    fprintf(stderr, "load failed: %s\n", paddle_tpu_last_error());
    return 1;
  }
  char* info = paddle_tpu_model_info(m);
  printf("%s\n", info);
  free(info);

  float* x = (float*)malloc(sizeof(float) * 2 * dim);
  for (int i = 0; i < 2 * dim; i++) x[i] = (float)i / (2.0f * dim);
  long long shape[2] = {2, dim};
  if (paddle_tpu_feed(m, "x", "float32", x, shape, 2, NULL, 0) != 0) {
    fprintf(stderr, "feed failed: %s\n", paddle_tpu_last_error());
    return 1;
  }
  if (paddle_tpu_forward(m, "o") != 0) {
    fprintf(stderr, "forward failed: %s\n", paddle_tpu_last_error());
    return 1;
  }
  const float* out;
  const long long* oshape;
  int ondim;
  if (paddle_tpu_output(m, "o", &out, &oshape, &ondim) != 0) {
    fprintf(stderr, "output failed: %s\n", paddle_tpu_last_error());
    return 1;
  }
  printf("out shape:");
  long long n = 1;
  for (int i = 0; i < ondim; i++) {
    printf(" %lld", oshape[i]);
    n *= oshape[i];
  }
  printf("\nvalues:");
  for (long long i = 0; i < n; i++) printf(" %.6f", out[i]);
  printf("\n");

  /* error-path probe: unknown output name must fail cleanly */
  if (paddle_tpu_forward(m, "nope") == 0) {
    fprintf(stderr, "expected failure for unknown output\n");
    return 1;
  }
  printf("unknown-output error: %s\n", paddle_tpu_last_error());

  paddle_tpu_model_destroy(m);
  free(x);
  return 0;
}
