// paddle_tpu native data-pipeline core.
//
// TPU-native analog of the reference's C++ data-provider machinery
// (reference: paddle/gserver/dataproviders/PyDataProvider2.cpp — background
// batch assembly, shuffle pool, DataBatch construction; and the flat-sequence
// Argument packing in paddle/parameter/Argument.cpp).  The Python feeder calls
// into this library via ctypes for the per-batch hot path: shuffling, length
// bucketing, and padded batch assembly into preallocated buffers — so the
// host side keeps TPU input queues fed without a Python inner loop.
//
// Build: g++ -O3 -shared -fPIC -o libpaddletpu_dataio.so dataio.cc
// Pure C ABI; no dependencies.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// shuffle
// ---------------------------------------------------------------------------

// Fisher–Yates permutation of [0, n) with a deterministic seed.
void ptd_shuffle_indices(int32_t n, uint64_t seed, int32_t* out) {
  for (int32_t i = 0; i < n; ++i) out[i] = i;
  std::mt19937_64 rng(seed);
  for (int32_t i = n - 1; i > 0; --i) {
    std::uniform_int_distribution<int32_t> dist(0, i);
    std::swap(out[i], out[dist(rng)]);
  }
}

// ---------------------------------------------------------------------------
// length bucketing
// ---------------------------------------------------------------------------

// For each length, the smallest bucket >= len (last bucket if none). Returns
// bucket *index* per row; used to group rows so XLA sees few shapes.
void ptd_bucket_by_length(const int32_t* lens, int32_t n, const int32_t* buckets,
                          int32_t n_buckets, int32_t* bucket_idx_out) {
  for (int32_t i = 0; i < n; ++i) {
    int32_t b = n_buckets - 1;
    for (int32_t j = 0; j < n_buckets; ++j) {
      if (lens[i] <= buckets[j]) { b = j; break; }
    }
    bucket_idx_out[i] = b;
  }
}

// Argsort rows by length (stable) — batch rows of similar length together
// (the reference sorts by length inside SequenceToBatch; here it minimizes
// padding waste per bucket).
void ptd_argsort_by_length(const int32_t* lens, int32_t n, int32_t* order_out) {
  for (int32_t i = 0; i < n; ++i) order_out[i] = i;
  std::stable_sort(order_out, order_out + n,
                   [&](int32_t a, int32_t b) { return lens[a] < lens[b]; });
}

// ---------------------------------------------------------------------------
// padded batch assembly
// ---------------------------------------------------------------------------

// Pack n variable-length int32 id sequences (concatenated in `flat`, row i
// spanning offsets[i]..offsets[i+1]) into out[n, maxT] zero-padded, clipping
// at maxT. out_lens receives the (clipped) lengths.
void ptd_pad_batch_i32(const int32_t* flat, const int64_t* offsets, int32_t n,
                       int32_t maxT, int32_t* out, int32_t* out_lens) {
  std::memset(out, 0, sizeof(int32_t) * (size_t)n * (size_t)maxT);
  for (int32_t i = 0; i < n; ++i) {
    int64_t start = offsets[i];
    int32_t len = (int32_t)std::min<int64_t>(offsets[i + 1] - start, maxT);
    std::memcpy(out + (size_t)i * maxT, flat + start, sizeof(int32_t) * (size_t)len);
    out_lens[i] = len;
  }
}

// Same for float rows with feature dim D: flat is [sum_len, D] row-major.
void ptd_pad_batch_f32(const float* flat, const int64_t* offsets, int32_t n,
                       int32_t maxT, int32_t D, float* out, int32_t* out_lens) {
  std::memset(out, 0, sizeof(float) * (size_t)n * (size_t)maxT * (size_t)D);
  for (int32_t i = 0; i < n; ++i) {
    int64_t start = offsets[i];
    int32_t len = (int32_t)std::min<int64_t>(offsets[i + 1] - start, maxT);
    std::memcpy(out + (size_t)i * maxT * D, flat + start * D,
                sizeof(float) * (size_t)len * (size_t)D);
    out_lens[i] = len;
  }
}

// ---------------------------------------------------------------------------
// sequence packing (segment ids) — long-context path
// ---------------------------------------------------------------------------

// Greedy first-fit packing of sequences into `n_rows` rows of capacity `T`.
// Writes packed ids, segment ids (1-based; 0 = padding) and per-row used
// lengths. Returns number of sequences that fit.
int32_t ptd_pack_sequences(const int32_t* flat, const int64_t* offsets,
                           int32_t n, int32_t n_rows, int32_t T,
                           int32_t* out_ids, int32_t* out_seg,
                           int32_t* row_used) {
  std::memset(out_ids, 0, sizeof(int32_t) * (size_t)n_rows * T);
  std::memset(out_seg, 0, sizeof(int32_t) * (size_t)n_rows * T);
  std::memset(row_used, 0, sizeof(int32_t) * (size_t)n_rows);
  int32_t placed = 0;
  for (int32_t i = 0; i < n; ++i) {
    int32_t len = (int32_t)(offsets[i + 1] - offsets[i]);
    if (len > T) continue;
    for (int32_t r = 0; r < n_rows; ++r) {
      if (row_used[r] + len <= T) {
        int32_t off = row_used[r];
        std::memcpy(out_ids + (size_t)r * T + off, flat + offsets[i],
                    sizeof(int32_t) * (size_t)len);
        for (int32_t t = 0; t < len; ++t)
          out_seg[(size_t)r * T + off + t] = placed + 1;
        row_used[r] += len;
        ++placed;
        break;
      }
    }
  }
  return placed;
}

// ---------------------------------------------------------------------------
// vocab / token stats (corpus preprocessing)
// ---------------------------------------------------------------------------

// Count token frequencies below `vocab_cap` into counts (caller-zeroed).
void ptd_count_tokens(const int32_t* flat, int64_t n_tokens, int32_t vocab_cap,
                      int64_t* counts) {
  for (int64_t i = 0; i < n_tokens; ++i) {
    int32_t t = flat[i];
    if (t >= 0 && t < vocab_cap) ++counts[t];
  }
}

int32_t ptd_version() { return 1; }

}  // extern "C"
