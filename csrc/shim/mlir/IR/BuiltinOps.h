// SHIM for the Python-free aot_host build ONLY (csrc/aot_host.cc).
//
// The tensorflow wheel ships MLIR headers but not LLVM's support headers,
// so the real BuiltinOps.h cannot be included.  xla/pjrt/pjrt_client.h
// needs mlir::ModuleOp solely as a by-value parameter of two inline
// virtual overloads the host never calls (their bodies return
// UnimplementedError without touching the value), so a minimal complete
// type satisfies the compiler; the emitted weak vtable thunks have the
// same mangled names and equivalent behavior as the library's.
#ifndef PADDLE_TPU_CSRC_SHIM_MLIR_BUILTIN_OPS_H_
#define PADDLE_TPU_CSRC_SHIM_MLIR_BUILTIN_OPS_H_

namespace mlir {
class ModuleOp {};
}  // namespace mlir

#endif  // PADDLE_TPU_CSRC_SHIM_MLIR_BUILTIN_OPS_H_
