// C inference API — analog of the reference's paddle/capi tier
// (capi/gradient_machine.h:27-59: create_for_inference,
// load_parameter_from_disk, forward; opaque handles capi/capi.h).
//
// The reference exposes its C++ inference engine through a pure-C surface so
// trained models deploy into non-C++ hosts.  Here the engine is the
// JAX-backed InferenceModel (paddle_tpu/config/deploy.py) serving a merged
// bundle (config proto + params); this file embeds CPython — the same
// technique the reference itself uses for config parsing
// (TrainerConfigHelper.cpp:33-54 via utils/PythonUtil.h) — and drives
// load_inference_model/infer behind opaque C handles.  XLA does the actual
// compute, so the C host gets jitted TPU/CPU inference with zero Python in
// its own code.
//
// Build:
//   g++ -O2 -shared -fPIC -std=c++17 csrc/capi.cc \
//       $(python3-config --includes) $(python3-config --ldflags --embed) \
//       -o paddle_tpu/_native/libpaddletpu_capi.so
//
// Thread model: any thread may call any function; each entry point takes the
// GIL (PyGILState_Ensure) and releases it on exit.

#include <Python.h>

#include <cstring>
#include <string>

namespace {

struct Model {
  PyObject* model = nullptr;    // InferenceModel instance
  PyObject* feed = nullptr;     // dict being assembled
  PyObject* outputs = nullptr;  // last infer() result dict
  PyObject* hold = nullptr;     // contiguous f32 array backing last output
  long long shape[16];
};

thread_local std::string g_error;

void set_error_from_python() {
  PyObject *type, *value, *trace;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  g_error = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) g_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
}

class Gil {
 public:
  Gil() : st_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st_); }

 private:
  PyGILState_STATE st_;
};

PyObject* np_module() {
  static PyObject* np = nullptr;
  if (!np) np = PyImport_ImportModule("numpy");
  return np;
}

// numpy array from raw host memory (copies, so the caller's buffer is free
// to die after the call)
PyObject* make_array(const char* dtype, const void* data,
                     const long long* shape, int ndim) {
  PyObject* np = np_module();
  if (!np) return nullptr;
  if (strcmp(dtype, "float32") != 0 && strcmp(dtype, "int32") != 0) {
    g_error = std::string("unsupported dtype '") + dtype +
              "' (use \"float32\" or \"int32\")";
    PyErr_SetString(PyExc_ValueError, g_error.c_str());
    return nullptr;
  }
  long long n = 1;
  for (int i = 0; i < ndim; i++) n *= shape[i];
  const size_t item = 4;  // float32 and int32 are both 4 bytes
  PyObject* mem = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)),
      static_cast<Py_ssize_t>(n * item), PyBUF_READ);
  if (!mem) return nullptr;
  PyObject* flat =
      PyObject_CallMethod(np, "frombuffer", "Os", mem, dtype);
  Py_DECREF(mem);
  if (!flat) return nullptr;
  PyObject* dims = PyTuple_New(ndim);
  for (int i = 0; i < ndim; i++)
    PyTuple_SET_ITEM(dims, i, PyLong_FromLongLong(shape[i]));
  PyObject* shaped = PyObject_CallMethod(flat, "reshape", "O", dims);
  Py_DECREF(flat);
  Py_DECREF(dims);
  if (!shaped) return nullptr;
  PyObject* copy = PyObject_CallMethod(shaped, "copy", nullptr);
  Py_DECREF(shaped);
  return copy;
}

}  // namespace

extern "C" {

// Start the embedded interpreter and import the framework. Returns 0 on
// success. Idempotent. (paddle_init analog, capi/main.h)
int paddle_tpu_init(void) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // release the GIL acquired by Py_Initialize so other threads (and our
    // Gil guards) can take it
    PyEval_SaveThread();
  }
  Gil gil;
  PyObject* m = PyImport_ImportModule("paddle_tpu.config.deploy");
  if (!m) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(m);
  return 0;
}

const char* paddle_tpu_last_error(void) { return g_error.c_str(); }

// Load a merged bundle (merge_model output). Returns NULL on failure.
// (paddle_gradient_machine_create_for_inference +
//  load_parameter_from_disk analog)
void* paddle_tpu_model_load(const char* bundle_path) {
  Gil gil;
  PyObject* m = PyImport_ImportModule("paddle_tpu.config.deploy");
  if (!m) {
    set_error_from_python();
    return nullptr;
  }
  PyObject* model =
      PyObject_CallMethod(m, "load_inference_model", "s", bundle_path);
  Py_DECREF(m);
  if (!model) {
    set_error_from_python();
    return nullptr;
  }
  Model* h = new Model();
  h->model = model;
  h->feed = PyDict_New();
  return h;
}

void paddle_tpu_model_destroy(void* handle) {
  if (!handle) return;
  Gil gil;
  Model* h = static_cast<Model*>(handle);
  Py_XDECREF(h->model);
  Py_XDECREF(h->feed);
  Py_XDECREF(h->outputs);
  Py_XDECREF(h->hold);
  delete h;
}

// Stage one input. dtype: "float32" | "int32". lengths (may be NULL) makes
// the feed a sequence (value, lengths) pair; n_lengths must equal shape[0].
int paddle_tpu_feed(void* handle, const char* name, const char* dtype,
                    const void* data, const long long* shape, int ndim,
                    const int* lengths, int n_lengths) {
  if (!handle || ndim < 1 || ndim > 16) {
    g_error = "bad handle or ndim";
    return -1;
  }
  Gil gil;
  Model* h = static_cast<Model*>(handle);
  PyObject* arr = make_array(dtype, data, shape, ndim);
  if (!arr) {
    set_error_from_python();
    return -1;
  }
  PyObject* entry = arr;
  if (lengths) {
    long long lshape[1] = {n_lengths};
    PyObject* larr = make_array("int32", lengths, lshape, 1);
    if (!larr) {
      Py_DECREF(arr);
      set_error_from_python();
      return -1;
    }
    entry = PyTuple_Pack(2, arr, larr);
    Py_DECREF(arr);
    Py_DECREF(larr);
  }
  int rc = PyDict_SetItemString(h->feed, name, entry);
  Py_DECREF(entry);
  if (rc != 0) set_error_from_python();
  return rc;
}

// Run inference on the staged feed (paddle_gradient_machine_forward analog).
// output_name may be NULL to compute the bundle's default outputs.
int paddle_tpu_forward(void* handle, const char* output_name) {
  if (!handle) {
    g_error = "bad handle";
    return -1;
  }
  Gil gil;
  Model* h = static_cast<Model*>(handle);
  PyObject* res;
  if (output_name) {
    PyObject* outs = PyList_New(1);
    PyList_SET_ITEM(outs, 0, PyUnicode_FromString(output_name));
    res = PyObject_CallMethod(h->model, "infer", "OO", h->feed, outs);
    Py_DECREF(outs);
  } else {
    res = PyObject_CallMethod(h->model, "infer", "O", h->feed);
  }
  if (!res) {
    set_error_from_python();
    return -1;
  }
  Py_XDECREF(h->outputs);
  h->outputs = res;
  return 0;
}

// Fetch a result as float32. *data stays valid until the next forward /
// output call or destroy.
int paddle_tpu_output(void* handle, const char* output_name,
                      const float** data, const long long** shape,
                      int* ndim) {
  if (!handle) {
    g_error = "bad handle";
    return -1;
  }
  Gil gil;
  Model* h = static_cast<Model*>(handle);
  if (!h->outputs) {
    g_error = "call paddle_tpu_forward first";
    return -1;
  }
  PyObject* arr = PyDict_GetItemString(h->outputs, output_name);  // borrowed
  if (!arr) {
    g_error = std::string("no output named '") + output_name + "'";
    return -1;
  }
  PyObject* np = np_module();
  PyObject* f32 = PyObject_CallMethod(np, "ascontiguousarray", "Os", arr,
                                      "float32");
  if (!f32) {
    set_error_from_python();
    return -1;
  }
  Py_XDECREF(h->hold);
  h->hold = f32;
  // data pointer + shape via the ctypes/shape attributes
  PyObject* sh = PyObject_GetAttrString(f32, "shape");
  int nd = static_cast<int>(PyTuple_Size(sh));
  for (int i = 0; i < nd && i < 16; i++)
    h->shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(sh, i));
  Py_DECREF(sh);
  PyObject* ct = PyObject_GetAttrString(f32, "ctypes");
  PyObject* ptr = ct ? PyObject_GetAttrString(ct, "data") : nullptr;
  Py_XDECREF(ct);
  if (!ptr) {
    set_error_from_python();
    return -1;
  }
  *data = reinterpret_cast<const float*>(PyLong_AsUnsignedLongLong(ptr));
  Py_DECREF(ptr);
  *shape = h->shape;
  *ndim = nd;
  return 0;
}

// Introspection: newline-joined input/output names. Caller must free().
char* paddle_tpu_model_info(void* handle) {
  if (!handle) return nullptr;
  Gil gil;
  Model* h = static_cast<Model*>(handle);
  PyObject* ins = PyObject_GetAttrString(h->model, "input_names");
  PyObject* outs = PyObject_GetAttrString(h->model, "output_names");
  std::string s = "inputs:";
  for (Py_ssize_t i = 0; ins && i < PyList_Size(ins); i++)
    s += std::string(" ") + PyUnicode_AsUTF8(PyList_GET_ITEM(ins, i));
  s += "\noutputs:";
  for (Py_ssize_t i = 0; outs && i < PyList_Size(outs); i++)
    s += std::string(" ") + PyUnicode_AsUTF8(PyList_GET_ITEM(outs, i));
  Py_XDECREF(ins);
  Py_XDECREF(outs);
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

}  // extern "C"
